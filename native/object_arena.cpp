// object_arena — shared-memory arena allocator for the ray_tpu object
// store.
//
// Equivalent role to the reference's plasma allocator
// (src/ray/object_manager/plasma/plasma_allocator.cc: dlmalloc over one
// mmap'd shm region) rebuilt from scratch: one file-backed mapping in
// /dev/shm per node, a best-fit free list with boundary-tag coalescing,
// 64-byte-aligned blocks. The node store process is the only allocator;
// reader processes attach the same file read-only and use offsets, so a
// process touching N objects costs one mmap, not N.
//
// C ABI (used from Python via ctypes):
//   arena_create(path, capacity)        -> handle (owner; truncates)
//   arena_attach(path)                  -> handle (reader)
//   arena_alloc(handle, size)           -> offset, or -1 if full
//   arena_free(handle, offset)          -> 0 ok / -1 bad offset
//   arena_base(handle)                  -> mapped base pointer
//   arena_capacity(handle)              -> usable bytes
//   arena_used(handle)                  -> allocated bytes (incl. headers)
//   arena_num_blocks(handle)            -> live allocation count
//   arena_close(handle, unlink)         -> unmap (+ unlink file)

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <new>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055'41524e41ULL;  // "RTPUARNA"
constexpr uint64_t kAlign = 64;
constexpr uint64_t kHeaderSize = 64;   // block header, one cache line
constexpr uint64_t kUsedBit = 1ULL << 63;

inline uint64_t align_up(uint64_t v, uint64_t a) {
  return (v + a - 1) & ~(a - 1);
}

// Block layout: [BlockHeader | payload ...]; blocks are physically
// contiguous, walked by size for coalescing. size field includes the
// header. prev_size lets us find the previous block for merging.
// refcnt counts mapper references: any attached process increments it
// while it hands out zero-copy views into the payload, so the owner
// can tell "no live reader" apart from "freed but maybe still aliased"
// (plasma analogue: the per-object client refcount in the store's
// object table). Updated with atomic builtins — the field lives in
// shared memory and is touched from multiple processes.
struct BlockHeader {
  uint64_t size_flags;   // size | kUsedBit
  uint64_t prev_size;    // size of physically-previous block (0 = first)
  uint64_t payload;      // requested payload size
  uint64_t refcnt;       // live mapper references (cross-process atomic)
  uint64_t pad[4];
  uint64_t size() const { return size_flags & ~kUsedBit; }
  bool used() const { return size_flags & kUsedBit; }
};
static_assert(sizeof(BlockHeader) == kHeaderSize, "header must be 64B");

// Arena file layout: [ArenaSuper | blocks ...]
struct ArenaSuper {
  uint64_t magic;
  uint64_t capacity;      // total bytes of block space
  uint64_t used;          // bytes allocated (incl. headers)
  uint64_t num_blocks;    // live allocations
};

struct Arena {
  ArenaSuper* super = nullptr;
  uint8_t* base = nullptr;       // start of block space
  uint64_t capacity = 0;
  bool owner = false;
  char path[4096] = {0};
  std::mutex mu;                 // allocator is single-process (owner)
};

BlockHeader* block_at(Arena* a, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(a->base + off);
}

}  // namespace

extern "C" {

void* arena_create(const char* path, uint64_t capacity) {
  capacity = align_up(capacity, kAlign);
  int fd = ::open(path, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(ArenaSuper) + capacity;
  total = align_up(total, 4096);
  if (::ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;

  Arena* a = new (std::nothrow) Arena();
  if (!a) { ::munmap(mem, total); return nullptr; }
  a->super = static_cast<ArenaSuper*>(mem);
  a->base = reinterpret_cast<uint8_t*>(mem) + sizeof(ArenaSuper);
  a->capacity = capacity;
  a->owner = true;
  std::strncpy(a->path, path, sizeof(a->path) - 1);

  a->super->magic = kMagic;
  a->super->capacity = capacity;
  a->super->used = 0;
  a->super->num_blocks = 0;
  // one giant free block
  BlockHeader* first = block_at(a, 0);
  first->size_flags = capacity;
  first->prev_size = 0;
  first->payload = 0;
  return a;
}

void* arena_attach(const char* path) {
  int fd = ::open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) { ::close(fd); return nullptr; }
  void* mem = ::mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  ArenaSuper* super = static_cast<ArenaSuper*>(mem);
  if (super->magic != kMagic) {
    ::munmap(mem, (size_t)st.st_size);
    return nullptr;
  }
  Arena* a = new (std::nothrow) Arena();
  if (!a) { ::munmap(mem, (size_t)st.st_size); return nullptr; }
  a->super = super;
  a->base = reinterpret_cast<uint8_t*>(mem) + sizeof(ArenaSuper);
  a->capacity = super->capacity;
  a->owner = false;
  std::strncpy(a->path, path, sizeof(a->path) - 1);
  return a;
}

// Best-fit scan over the free list (physical walk; blocks are few
// relative to bytes, and the walk is O(blocks)).
int64_t arena_alloc(void* handle, uint64_t payload) {
  Arena* a = static_cast<Arena*>(handle);
  if (!a || !a->owner) return -1;
  std::lock_guard<std::mutex> g(a->mu);
  uint64_t need = align_up(payload, kAlign) + kHeaderSize;
  uint64_t best_off = UINT64_MAX;
  uint64_t best_size = UINT64_MAX;
  uint64_t off = 0;
  while (off < a->capacity) {
    BlockHeader* b = block_at(a, off);
    uint64_t bsize = b->size();
    if (bsize == 0) break;  // corrupt; stop
    if (!b->used() && bsize >= need && bsize < best_size) {
      best_off = off;
      best_size = bsize;
      if (bsize == need) break;
    }
    off += bsize;
  }
  if (best_off == UINT64_MAX) return -1;

  BlockHeader* b = block_at(a, best_off);
  uint64_t remainder = best_size - need;
  if (remainder >= kHeaderSize + kAlign) {
    // split: tail stays free
    b->size_flags = need | kUsedBit;
    BlockHeader* tail = block_at(a, best_off + need);
    tail->size_flags = remainder;
    tail->prev_size = need;
    tail->payload = 0;
    uint64_t after_off = best_off + best_size;
    if (after_off < a->capacity)
      block_at(a, after_off)->prev_size = remainder;
  } else {
    need = best_size;
    b->size_flags = need | kUsedBit;
  }
  b->payload = payload;
  __atomic_store_n(&b->refcnt, 0, __ATOMIC_RELAXED);
  a->super->used += need;
  a->super->num_blocks += 1;
  return (int64_t)(best_off + kHeaderSize);
}

namespace {
// Shared validation for the refcount entry points: any attached process
// (owner or reader) may call them, so only offset sanity is checked.
BlockHeader* ref_block(void* handle, int64_t payload_off) {
  Arena* a = static_cast<Arena*>(handle);
  if (!a || payload_off < (int64_t)kHeaderSize) return nullptr;
  uint64_t off = (uint64_t)payload_off - kHeaderSize;
  if (off >= a->capacity) return nullptr;
  return block_at(a, off);
}
}  // namespace

int arena_free(void* handle, int64_t payload_off) {
  Arena* a = static_cast<Arena*>(handle);
  if (!a || !a->owner) return -1;
  std::lock_guard<std::mutex> g(a->mu);
  if (payload_off < (int64_t)kHeaderSize) return -1;
  uint64_t off = (uint64_t)payload_off - kHeaderSize;
  if (off >= a->capacity) return -1;
  BlockHeader* b = block_at(a, off);
  if (!b->used()) return -1;
  uint64_t size = b->size();
  a->super->used -= size;
  a->super->num_blocks -= 1;
  b->size_flags = size;
  b->payload = 0;

  // coalesce with next
  uint64_t next_off = off + size;
  if (next_off < a->capacity) {
    BlockHeader* next = block_at(a, next_off);
    if (!next->used()) {
      size += next->size();
      b->size_flags = size;
    }
  }
  // coalesce with prev
  if (b->prev_size) {
    BlockHeader* prev = block_at(a, off - b->prev_size);
    if (!prev->used()) {
      off -= b->prev_size;
      size += prev->size();
      prev->size_flags = size;
      b = prev;
    }
  }
  // fix prev_size of the block after the merged region
  uint64_t after_off = off + size;
  if (after_off < a->capacity)
    block_at(a, after_off)->prev_size = size;
  return 0;
}

int64_t arena_incref(void* handle, int64_t payload_off) {
  BlockHeader* b = ref_block(handle, payload_off);
  if (!b || !b->used()) return -1;
  return (int64_t)(__atomic_add_fetch(&b->refcnt, 1, __ATOMIC_ACQ_REL));
}

int64_t arena_decref(void* handle, int64_t payload_off) {
  BlockHeader* b = ref_block(handle, payload_off);
  if (!b) return -1;
  // decref may land after the owner already freed the block (reader
  // dropped its last view late); the count still balances because free
  // doesn't recycle header bytes until realloc, and alloc re-zeroes it.
  uint64_t prev = __atomic_fetch_sub(&b->refcnt, 1, __ATOMIC_ACQ_REL);
  if (prev == 0) {  // underflow guard: restore and report
    __atomic_store_n(&b->refcnt, 0, __ATOMIC_RELAXED);
    return -1;
  }
  return (int64_t)(prev - 1);
}

int64_t arena_refcount(void* handle, int64_t payload_off) {
  BlockHeader* b = ref_block(handle, payload_off);
  if (!b) return -1;
  return (int64_t)__atomic_load_n(&b->refcnt, __ATOMIC_ACQUIRE);
}

uint8_t* arena_base(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  return a ? a->base : nullptr;
}

uint64_t arena_capacity(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  return a ? a->capacity : 0;
}

uint64_t arena_used(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  return a ? a->super->used : 0;
}

uint64_t arena_num_blocks(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  return a ? a->super->num_blocks : 0;
}

void arena_close(void* handle, int unlink_file) {
  Arena* a = static_cast<Arena*>(handle);
  if (!a) return;
  uint64_t total = align_up(sizeof(ArenaSuper) + a->capacity, 4096);
  ::munmap(a->super, total);
  if (unlink_file && a->owner) ::unlink(a->path);
  delete a;
}

}  // extern "C"
