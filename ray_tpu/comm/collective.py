"""Host-level collective communication: peer-to-peer pipelined rings.

API mirrors the reference's ``util/collective/collective.py:258-615``
(allreduce/allgather/reducescatter/broadcast/send/recv/barrier, group
init by world_size+rank+group_name). Where the reference backs these
with NCCL/Gloo process groups, here the data plane is the node-plane
zero-copy transport (``_private/coll_transport.py``): ranks exchange
tensor chunks peer to peer as out-of-band pickle-5 iovecs, and
completion is driven by connection reader threads waking condition
variables — no polling anywhere on the data path.

Algorithms (reference model: "The Big Send-off" / bandwidth-optimal
collective schedules):

- **ring allreduce** = reduce-scatter + allgather over the rank ring,
  tensors split into ``collective_chunk_bytes`` chunks so chunk k+1
  transmits while chunk k reduces; per-rank wire traffic is
  ~2x tensor size, independent of world size.
- **ring reduce-scatter / allgather** reuse the two ring phases.
- **binomial-tree broadcast** (chunk-pipelined down the tree) and a
  small-payload **tree allreduce** below
  ``collective_tree_threshold_bytes`` (latency-bound regime: 2·log2(w)
  hops beat a 2·(w-1)-step ring).
- **send/recv** are direct rank-to-rank mailbox messages.

The named ``_Coordinator`` actor is control plane only: group
membership, rank -> endpoint exchange, epoch agreement — plus a
degenerate fallback data path (``collective_p2p_enabled=False`` or a
rank with no runtime endpoint) that reduces by streaming pairwise
accumulation on waiter futures (O(size) peak memory, no polling).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import get, get_actor
from ..api import remote
from .._private import coll_transport
from .._private import locksan
from .._private import telemetry
from .._private.config import CONFIG

_GROUP_ACTOR_PREFIX = "rtpu:collective:"

M_COLL_LATENCY = telemetry.define(
    "histogram", "rtpu_collective_latency_seconds",
    "End-to-end latency of one host-level collective call, tagged by "
    "op and group (the communication axis)")
M_COLL_BYTES = telemetry.define(
    "counter", "rtpu_collective_bytes_total",
    "Payload bytes contributed to collectives by this rank")
M_COLL_OPS = telemetry.define(
    "counter", "rtpu_collective_ops_total",
    "Collective calls completed by this rank")


def _observe(op: str, group: str, nbytes: int, t0: float) -> None:
    tags = (("group", group), ("op", op))
    telemetry.counter_inc(M_COLL_OPS, 1.0, tags)
    if nbytes:
        telemetry.counter_inc(M_COLL_BYTES, float(nbytes), tags)
    telemetry.hist_observe(M_COLL_LATENCY, time.monotonic() - t0, tags)

# ops
SUM = "sum"
PROD = "prod"
MIN = "min"
MAX = "max"

# binary ufuncs: streaming pairwise accumulation keeps peak memory at
# O(size) (the seed's np.stack over world_size arrays was O(world*size))
# and, unlike np.sum's axis reduction, never promotes the dtype
_BINARY = {SUM: np.add, PROD: np.multiply, MIN: np.minimum, MAX: np.maximum}


class _CoordinatorImpl:
    """Control plane of one collective group (async actor).

    Owns membership (rank -> endpoint exchange under a fresh group
    epoch) and the degenerate fallback data path. Every blocking call
    awaits an ``asyncio.Event`` resolved by the completing member —
    callers block on the actor reply, never on a poll loop. Call
    records a timed-out rank abandoned (and mailbox posts never taken)
    are swept once they outlive ``ttl_s``.
    """

    def __init__(self, world_size: int, ttl_s: Optional[float] = None):
        self.world_size = world_size
        self.epoch = os.urandom(8).hex()
        self.ttl_s = float(ttl_s if ttl_s is not None
                           else CONFIG.collective_call_ttl_s)
        self._endpoints: Dict[int, Any] = {}
        self._join_ev = asyncio.Event()
        self._calls: Dict[tuple, dict] = {}
        self._mail: Dict[tuple, tuple] = {}          # key -> (value, born)
        self._mail_evs: Dict[tuple, asyncio.Event] = {}

    def ping(self) -> bool:
        return True

    def debug_counts(self) -> Dict[str, int]:
        """Test surface: live fallback-call records and mailbox posts."""
        self._sweep()
        return {"calls": len(self._calls), "mail": len(self._mail)}

    def _sweep(self) -> None:
        """Drop records older than the TTL: a rank that timed out of a
        rendezvous leaves a partial record behind, and an un-taken post
        has no reader — neither may live forever."""
        now = time.monotonic()
        for key, rec in list(self._calls.items()):
            if now - rec["born"] > self.ttl_s:
                rec["expired"] = True
                rec["ev"].set()
                del self._calls[key]
        for key, (_value, born) in list(self._mail.items()):
            if now - born > self.ttl_s:
                del self._mail[key]

    # ------------------------------------------------------- membership
    async def join(self, rank: int, endpoint, timeout_s: float):
        """Register this rank's endpoint; resolves for everyone once
        all world_size ranks arrived. Returns (epoch, endpoints)."""
        self._endpoints[rank] = (tuple(endpoint) if endpoint is not None
                                 else None)
        if len(self._endpoints) >= self.world_size:
            self._join_ev.set()
        else:
            try:
                await asyncio.wait_for(self._join_ev.wait(), timeout_s)
            except asyncio.TimeoutError:
                missing = [r for r in range(self.world_size)
                           if r not in self._endpoints]
                return ("timeout",
                        f"ranks {missing} never joined the group")
        eps = [self._endpoints.get(r) for r in range(self.world_size)]
        return ("ok", (self.epoch, eps))

    # ------------------------------------------- fallback data path
    def _call(self, key) -> dict:
        rec = self._calls.get(key)
        if rec is None:
            rec = {"count": 0, "acc": None, "parts": {}, "result": None,
                   "done": False, "taken": 0, "expired": False,
                   "born": time.monotonic(), "ev": asyncio.Event()}
            self._calls[key] = rec
        return rec

    async def rendezvous(self, key, rank: int, value, op: Optional[str],
                         timeout_s: float):
        """Blocking rendezvous: contribution ``world_size`` resolves the
        waiters. ``op`` None gathers parts (allgather/broadcast/barrier);
        otherwise the reduction accumulates pairwise as values arrive."""
        self._sweep()
        rec = self._call(key)
        if op is None:
            # copy: the deserialized view may alias a store segment that
            # is unpinned once this call returns
            rec["parts"][rank] = (np.array(value)
                                  if isinstance(value, np.ndarray)
                                  else value)
        else:
            v = np.asarray(value)
            rec["acc"] = (np.array(v) if rec["acc"] is None
                          else _BINARY[op](rec["acc"], v))
        rec["count"] += 1
        if rec["count"] >= self.world_size:
            rec["result"] = (rec["acc"] if op is not None else
                             [rec["parts"].get(r)
                              for r in range(self.world_size)])
            rec["done"] = True
            rec["ev"].set()
        elif not rec["done"]:
            try:
                await asyncio.wait_for(rec["ev"].wait(), timeout_s)
            except asyncio.TimeoutError:
                # leave the partial record for the TTL sweep
                return ("timeout",
                        f"{rec['count']}/{self.world_size} ranks arrived")
        if rec["expired"]:
            return ("timeout", "call record expired (TTL sweep)")
        rec["taken"] += 1
        if rec["taken"] >= self.world_size:
            self._calls.pop(key, None)
        return ("ok", rec["result"])

    async def post(self, dst_rank: int, tag, value) -> None:
        self._sweep()
        key = (dst_rank, tuple(tag))
        self._mail[key] = (np.array(value)
                           if isinstance(value, np.ndarray) else value,
                           time.monotonic())
        ev = self._mail_evs.get(key)
        if ev is not None:
            ev.set()

    async def take(self, dst_rank: int, tag, timeout_s: float):
        self._sweep()
        key = (dst_rank, tuple(tag))
        if key not in self._mail:
            ev = self._mail_evs.get(key)
            if ev is None:
                ev = self._mail_evs[key] = asyncio.Event()
            try:
                await asyncio.wait_for(ev.wait(), timeout_s)
            except asyncio.TimeoutError:
                return ("timeout", f"no message for tag {tag}")
            finally:
                self._mail_evs.pop(key, None)
        if key not in self._mail:            # raced the TTL sweep
            return ("timeout", "message expired (TTL sweep)")
        value, _born = self._mail.pop(key)
        return ("ok", value)


_Coordinator = remote(num_cpus=0)(_CoordinatorImpl)


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, coordinator,
                 epoch: str, endpoints: List[Any]):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.epoch = epoch
        self.endpoints = endpoints
        # p2p only when every rank published a routable endpoint (all
        # ranks derive this from the same exchanged data, so the whole
        # group agrees on the schedule)
        self.use_p2p = all(ep is not None for ep in endpoints)
        self.seq = 0
        # p2p sequence counters keyed by (peer_rank, tag)
        self.send_seq: Dict[tuple, int] = {}
        self.recv_seq: Dict[tuple, int] = {}

    def next_seq(self) -> int:
        seq = self.seq
        self.seq += 1
        return seq

    def key(self, seq: int) -> tuple:
        return (self.name, self.epoch, seq)


# Per-process registry (module-global like the reference's GroupManager,
# ``collective.py:40``; actor methods may run on different threads).
_process_groups: Dict[str, _GroupState] = {}
_groups_lock = locksan.lock("collective.groups")


def _groups() -> Dict[str, _GroupState]:
    return _process_groups


def _coord(state_or_actor, method: str, *args):
    """Call a coordinator method and unwrap its ("ok"|"timeout", x)
    status tuple; "timeout" raises here so every rank surfaces it."""
    res = get(getattr(state_or_actor, method).remote(*args))
    if res[0] != "ok":
        raise TimeoutError(f"collective {method}: {res[1]}")
    return res[1]


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join a collective group (reference: ``collective.py:120``).

    Call from every member actor/task with a distinct ``rank``. Rank 0
    creates the named coordinator actor; others look it up. All members
    then exchange (rank -> endpoint) through the coordinator, which is
    what the peer-to-peer ring/tree schedules route on.
    """
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    actor_name = _GROUP_ACTOR_PREFIX + group_name
    coordinator = None
    if rank == 0:
        coordinator = _Coordinator.options(name=actor_name).remote(world_size)
        # touch it so registration completes before others look it up
        get(coordinator.ping.remote())
    else:
        deadline = time.monotonic() + 30.0
        while True:                 # control plane (init only): the
            try:                    # data path never polls
                coordinator = get_actor(actor_name)
                break
            except ValueError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"collective group {group_name!r}: coordinator "
                        "never appeared (is rank 0 up?)")
                time.sleep(0.02)
    ep = (coll_transport.local_endpoint()
          if CONFIG.collective_p2p_enabled else None)
    epoch, endpoints = _coord(coordinator, "join", rank, ep,
                              CONFIG.collective_timeout_s)
    with _groups_lock:
        _process_groups[group_name] = _GroupState(
            group_name, world_size, rank, coordinator, epoch, endpoints)


class CollectiveActorMixin:
    """Mix into an actor class to make it driveable by
    ``create_collective_group`` (and get convenience methods)."""

    def _rtpu_init_collective(self, world_size: int, rank: int,
                              group_name: str) -> None:
        init_collective_group(world_size, rank, group_name)

    def _rtpu_destroy_collective(self, group_name: str) -> None:
        destroy_collective_group(group_name)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int],
                            group_name: str = "default") -> None:
    """Driver-side declarative setup (reference: ``collective.py:177``):
    instructs each actor to call ``init_collective_group``. Actor classes
    must inherit ``CollectiveActorMixin`` (or expose an equivalent
    ``_rtpu_init_collective`` method).

    Rank 0's init creates the coordinator and later ranks block on its
    appearance, so all members are driven concurrently here.
    """
    if len(actors) != world_size or len(ranks) != world_size:
        raise ValueError(
            f"need exactly world_size={world_size} actors and ranks, got "
            f"{len(actors)} actors / {len(ranks)} ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size-1}, "
                         f"got {ranks}")
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor._rtpu_init_collective.remote(world_size, rank,
                                                       group_name))
    get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        state = _process_groups.pop(group_name, None)
    if state is None:
        return
    coll_transport.drop_group(state.name, state.epoch)
    if state.rank == 0:
        from .. import kill
        try:
            kill(state.coordinator)
        except Exception:
            pass


def get_rank(group_name: str = "default") -> int:
    state = _groups().get(group_name)
    return -1 if state is None else state.rank


def get_collective_group_size(group_name: str = "default") -> int:
    state = _groups().get(group_name)
    return -1 if state is None else state.world_size


def _state(group_name: str) -> _GroupState:
    state = _groups().get(group_name)
    if state is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            "process; call init_collective_group first")
    return state


def _to_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


def _deadline(timeout: Optional[float]) -> float:
    return time.monotonic() + (timeout if timeout is not None
                               else CONFIG.collective_timeout_s)


def _timeout_s(timeout: Optional[float]) -> float:
    return timeout if timeout is not None else CONFIG.collective_timeout_s


# --------------------------------------------------------- ring schedules
#
# Ring convention (delta = -1): at reduce-scatter step s, rank r sends
# segment (r-1-s) mod w and receives segment (r-2-s) mod w from its left
# neighbor, reducing it into the local buffer — after w-1 steps rank r
# holds segment r fully reduced. The allgather phase then circulates the
# finished segments the same way. Chunks pipeline: a chunk is forwarded
# the moment it is reduced, so chunk k+1 is on the wire while chunk k
# reduces, and a chunk's buffer is never mutated again until the data
# derived from it has causally passed through the next rank (which makes
# the zero-copy views safe).

def _chunk_ranges(a: int, b: int, chunk_elems: int) -> List[Tuple[int, int]]:
    out = []
    while a < b:
        e = min(a + chunk_elems, b)
        out.append((a, e))
        a = e
    return out


def _chunk_elems(dtype) -> int:
    return max(1, CONFIG.collective_chunk_bytes // max(1, dtype.itemsize))


def _send(state: _GroupState, dst_rank: int, key: tuple, payload,
          op: str) -> None:
    coll_transport.send(state.endpoints[dst_rank], key, payload,
                        group=state.name, op=op)


def _ring_reduce_scatter(state: _GroupState, buf: np.ndarray,
                         bounds: List[int], op: str, key: tuple,
                         deadline: float, opname: str) -> None:
    """In-place ring reduce-scatter over ``buf`` segments ``bounds``;
    on return segment ``rank`` holds the full reduction."""
    w, r = state.world_size, state.rank
    right = (r + 1) % w
    ce = _chunk_elems(buf.dtype)
    binop = _BINARY[op]

    def chunks(seg: int) -> List[Tuple[int, int]]:
        return _chunk_ranges(bounds[seg], bounds[seg + 1], ce)

    first = (r - 1) % w
    for ci, (a, b) in enumerate(chunks(first)):
        _send(state, right, key + ("rs", first, ci), buf[a:b], opname)
    for s in range(w - 1):
        seg = (r - 2 - s) % w
        for ci, (a, b) in enumerate(chunks(seg)):
            data = coll_transport.wait(key + ("rs", seg, ci), deadline)
            view = buf[a:b]
            binop(view, np.asarray(data), out=view)
            if s < w - 2:
                # forward the just-reduced chunk while the next chunk
                # of this segment is still in flight (pipelining)
                _send(state, right, key + ("rs", seg, ci), view, opname)


def _ring_allgather_segments(state: _GroupState, buf: np.ndarray,
                             bounds: List[int], key: tuple,
                             deadline: float, opname: str) -> None:
    """Ring allgather of ``buf`` segments: each rank starts with its own
    segment final (post reduce-scatter) and circulates; on return every
    segment of ``buf`` is final."""
    w, r = state.world_size, state.rank
    right = (r + 1) % w
    ce = _chunk_elems(buf.dtype)

    def chunks(seg: int) -> List[Tuple[int, int]]:
        return _chunk_ranges(bounds[seg], bounds[seg + 1], ce)

    for ci, (a, b) in enumerate(chunks(r)):
        _send(state, right, key + ("ag", r, ci), buf[a:b], opname)
    for s in range(w - 1):
        seg = (r - 1 - s) % w
        for ci, (a, b) in enumerate(chunks(seg)):
            data = coll_transport.wait(key + ("ag", seg, ci), deadline)
            if s < w - 2:
                # forward the received (zero-copy) view untouched
                _send(state, right, key + ("ag", seg, ci), data, opname)
            buf[a:b] = np.asarray(data)


# --------------------------------------------------------- tree schedules

def _tree_parent_children(v: int, w: int) -> Tuple[Optional[int], List[int]]:
    """Binomial tree rooted at virtual rank 0: parent clears v's lowest
    set bit; children are v + m for descending m below it."""
    if v == 0:
        lsb = 1
        while lsb < w:
            lsb <<= 1
        parent = None
    else:
        lsb = v & -v
        parent = v - lsb
    children = []
    m = lsb >> 1
    while m:
        if v + m < w:
            children.append(v + m)
        m >>= 1
    return parent, children


def _tree_reduce(state: _GroupState, arr: np.ndarray, op: str, key: tuple,
                 deadline: float, opname: str) -> Optional[np.ndarray]:
    """Binomial-tree reduction to rank 0; returns the total at rank 0,
    None elsewhere (small payloads: whole arrays per hop)."""
    w, r = state.world_size, state.rank
    binop = _BINARY[op]
    acc = np.array(arr)
    mask = 1
    while mask < w:
        if r & mask:
            _send(state, r - mask, key + ("tr", r), acc, opname)
            return None
        peer = r | mask
        if peer < w:
            data = coll_transport.wait(key + ("tr", peer), deadline)
            acc = binop(acc, np.asarray(data))
        mask <<= 1
    return acc


def _tree_bcast_small(state: _GroupState, data, src_rank: int, key: tuple,
                      deadline: float, opname: str) -> np.ndarray:
    """Whole-payload binomial broadcast (small/known-shape payloads)."""
    w, r = state.world_size, state.rank
    v = (r - src_rank) % w
    parent, children = _tree_parent_children(v, w)
    if parent is not None:
        data = coll_transport.wait(key + ("tb", v), deadline)
    for c in children:
        _send(state, (c + src_rank) % w, key + ("tb", c), data, opname)
    return np.asarray(data)


def _tree_bcast_chunked(state: _GroupState, value: Optional[np.ndarray],
                        src_rank: int, key: tuple, deadline: float,
                        opname: str) -> np.ndarray:
    """Chunk-pipelined binomial broadcast: non-source ranks learn the
    shape from a header, then each chunk is forwarded down the tree the
    moment it arrives (chunk k+1 rides the wire while k lands)."""
    w, r = state.world_size, state.rank
    v = (r - src_rank) % w
    parent, children = _tree_parent_children(v, w)

    def fanout(subkey: tuple, payload) -> None:
        for c in children:
            _send(state, (c + src_rank) % w, key + subkey + (c,), payload,
                  opname)

    if parent is None:
        flat = np.ascontiguousarray(value).reshape(-1)
        ranges = _chunk_ranges(0, flat.size, _chunk_elems(flat.dtype))
        header = (value.shape, flat.dtype.str, len(ranges))
        fanout(("bh",), header)
        for ci, (a, b) in enumerate(ranges):
            fanout(("bc", ci), flat[a:b])
        return np.asarray(value)
    shape, dtype_str, nchunks = coll_transport.wait(
        key + ("bh", v), deadline)
    fanout(("bh",), (shape, dtype_str, nchunks))
    buf = np.empty(int(np.prod(shape, dtype=np.int64)),
                   dtype=np.dtype(dtype_str))
    pos = 0
    for ci in range(nchunks):
        data = coll_transport.wait(key + ("bc", ci, v), deadline)
        fanout(("bc", ci), data)
        arr = np.asarray(data)
        buf[pos:pos + arr.size] = arr
        pos += arr.size
    return buf.reshape(tuple(shape))


# ------------------------------------------------------------- public API

def allreduce(tensor, group_name: str = "default", op: str = SUM,
              timeout: Optional[float] = None):
    """All-reduce; returns the reduced array (reference mutates in place —
    functional style here, jax arrays are immutable). Ring reduce-scatter
    + allgather above ``collective_tree_threshold_bytes``, binomial tree
    below it; every rank returns bit-identical bytes."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    if state.world_size == 1:
        result = np.array(arr)
    elif not state.use_p2p:
        result = np.asarray(_coord(state.coordinator, "rendezvous",
                                   state.key(seq), state.rank, arr, op,
                                   _timeout_s(timeout)))
    elif arr.nbytes < CONFIG.collective_tree_threshold_bytes:
        key, deadline = state.key(seq), _deadline(timeout)
        total = _tree_reduce(state, arr, op, key, deadline, "allreduce")
        result = _tree_bcast_small(state, total, 0, key, deadline,
                                   "allreduce").reshape(arr.shape)
        # the fanned-out buffer aliases the returned array (root) — the
        # caller may mutate it the moment we return, so the zero-copy
        # sends must have left this process first
        coll_transport.flush()
    else:
        key, deadline = state.key(seq), _deadline(timeout)
        flat = np.ascontiguousarray(arr).reshape(-1)
        buf = flat.copy()
        n = buf.size
        w = state.world_size
        bounds = [(i * n) // w for i in range(w + 1)]
        _ring_reduce_scatter(state, buf, bounds, op, key, deadline,
                             "allreduce")
        _ring_allgather_segments(state, buf, bounds, key, deadline,
                                 "allreduce")
        # allgather-phase sends are views of ``buf``, which the caller
        # receives (and may mutate) as the result — flush before return
        coll_transport.flush()
        result = buf.reshape(arr.shape)
    _observe("allreduce", group_name, arr.nbytes, t0)
    return result


def allgather(tensor, group_name: str = "default",
              timeout: Optional[float] = None) -> List[np.ndarray]:
    """Gather every rank's array (whole contributions circulate the
    ring; output is inherently O(world * size))."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    w, r = state.world_size, state.rank
    if w == 1:
        parts: List[np.ndarray] = [np.array(arr)]
    elif not state.use_p2p:
        parts = [np.asarray(p) for p in _coord(
            state.coordinator, "rendezvous", state.key(seq), r, arr,
            None, _timeout_s(timeout))]
    else:
        key, deadline = state.key(seq), _deadline(timeout)
        out: List[Any] = [None] * w
        out[r] = arr
        right = (r + 1) % w
        _send(state, right, key + ("ga", r), arr, "allgather")
        for s in range(w - 1):
            src = (r - 1 - s) % w
            data = coll_transport.wait(key + ("ga", src), deadline)
            if s < w - 2:
                _send(state, right, key + ("ga", src), data, "allgather")
            out[src] = np.asarray(data)
        # the caller's own ``arr`` went onto the ring zero-copy and the
        # caller may mutate it once we return — flush the link first
        coll_transport.flush()
        parts = [np.asarray(p) for p in out]
    _observe("allgather", group_name, arr.nbytes, t0)
    return parts


def reducescatter(tensor, group_name: str = "default", op: str = SUM,
                  timeout: Optional[float] = None):
    """Reduce then return this rank's 1/world_size slice along axis 0
    (ring reduce-scatter: each rank receives only its own slice's
    traffic, ~1x tensor size per rank)."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    w, r = state.world_size, state.rank
    if arr.ndim == 0 or arr.shape[0] % w:
        raise ValueError(
            f"reducescatter: leading dim {arr.shape[:1]} not divisible "
            f"by world size {w}")
    rows = arr.shape[0] // w
    if w == 1:
        result = np.array(arr)
    elif not state.use_p2p:
        reduced = np.asarray(_coord(state.coordinator, "rendezvous",
                                    state.key(seq), r, arr, op,
                                    _timeout_s(timeout)))
        result = reduced[r * rows:(r + 1) * rows]
    else:
        key, deadline = state.key(seq), _deadline(timeout)
        flat = np.ascontiguousarray(arr).reshape(-1)
        buf = flat.copy()
        seg_elems = rows * (flat.size // arr.shape[0])
        bounds = [i * seg_elems for i in range(w + 1)]
        _ring_reduce_scatter(state, buf, bounds, op, key, deadline,
                             "reducescatter")
        result = buf[bounds[r]:bounds[r + 1]].reshape(
            (rows,) + arr.shape[1:]).copy()
    _observe("reducescatter", group_name, arr.nbytes, t0)
    return result


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: Optional[float] = None):
    """Binomial-tree broadcast from ``src_rank``, chunk-pipelined down
    the tree; non-source ranks' tensors are ignored (shape/dtype arrive
    in the header)."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    is_src = state.rank == src_rank
    if state.world_size == 1:
        result = np.array(arr)
    elif not state.use_p2p:
        parts = _coord(state.coordinator, "rendezvous", state.key(seq),
                       state.rank, arr if is_src else None, None,
                       _timeout_s(timeout))
        result = np.asarray(parts[src_rank])
    else:
        result = _tree_bcast_chunked(state, arr if is_src else None,
                                     src_rank, state.key(seq),
                                     _deadline(timeout), "broadcast")
        # the source fans out zero-copy views of the caller's tensor
        # (contiguous input: ascontiguousarray is a no-copy) — it must
        # be on the wire before the caller can touch it again
        coll_transport.flush()
    _observe("broadcast", group_name, arr.nbytes if is_src else 0, t0)
    return result


def barrier(group_name: str = "default",
            timeout: Optional[float] = None) -> None:
    """All ranks block until every rank arrived (tree reduce + tree
    broadcast of an empty token — 2·log2(w) hops)."""
    state = _state(group_name)
    t0 = time.monotonic()
    seq = state.next_seq()
    if state.world_size == 1:
        pass
    elif not state.use_p2p:
        _coord(state.coordinator, "rendezvous", state.key(seq),
               state.rank, None, None, _timeout_s(timeout))
    else:
        key, deadline = state.key(seq), _deadline(timeout)
        token = np.zeros(1, dtype=np.uint8)
        total = _tree_reduce(state, token, SUM, key, deadline, "barrier")
        _tree_bcast_small(state, total, 0, key, deadline, "barrier")
    _observe("barrier", group_name, 0, t0)


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    """Direct rank-to-rank send: one mailbox message straight to the
    destination rank's process (no coordinator hop)."""
    state = _state(group_name)
    seq = state.send_seq.get((dst_rank, tag), 0)
    state.send_seq[(dst_rank, tag)] = seq + 1
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    if state.use_p2p:
        _send(state, dst_rank,
              (state.name, state.epoch, "p2p", state.rank, dst_rank,
               tag, seq), arr, "send")
        # ``arr`` aliases the caller's tensor (zero-copy); send() must
        # not return while it can still be pickled later by a drainer
        coll_transport.flush()
    else:
        get(state.coordinator.post.remote(
            dst_rank, (state.rank, tag, seq), arr))
    _observe("send", group_name, arr.nbytes, t0)


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: Optional[float] = None):
    """Blocking receive of the matching ``send`` (FIFO per (src, tag));
    wakes on delivery, raises TimeoutError at the deadline."""
    state = _state(group_name)
    seq = state.recv_seq.get((src_rank, tag), 0)
    state.recv_seq[(src_rank, tag)] = seq + 1
    t0 = time.monotonic()
    if state.use_p2p:
        data = coll_transport.wait(
            (state.name, state.epoch, "p2p", src_rank, state.rank,
             tag, seq), _deadline(timeout), what="p2p recv")
        arr = np.array(data)
    else:
        arr = np.asarray(_coord(state.coordinator, "take", state.rank,
                                (src_rank, tag, seq),
                                _timeout_s(timeout)))
    _observe("recv", group_name, arr.nbytes, t0)
    return arr
