"""Host-level collective communication over the object plane.

API mirrors the reference's ``util/collective/collective.py:258-615``
(allreduce/allgather/reducescatter/broadcast/send/recv/barrier, group
init by world_size+rank+group_name). Where the reference backs these
with NCCL/Gloo process groups, here membership + rendezvous live in a
named **coordinator actor** and payloads ride the shared-memory object
store (zero-copy numpy) — the right transport for host arrays; device
arrays inside one slice should use in-program XLA collectives instead.

Reductions are computed once in the coordinator (numpy) rather than in a
ring: host-level groups are small (one member per host), and one
put+get through shm beats O(ranks) python-loop ring steps.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import get, get_actor, put
from ..api import remote
from .._private import telemetry

_GROUP_ACTOR_PREFIX = "rtpu:collective:"

M_COLL_LATENCY = telemetry.define(
    "histogram", "rtpu_collective_latency_seconds",
    "End-to-end latency of one host-level collective call, tagged by "
    "op and group (the communication axis)")
M_COLL_BYTES = telemetry.define(
    "counter", "rtpu_collective_bytes_total",
    "Payload bytes contributed to collectives by this rank")
M_COLL_OPS = telemetry.define(
    "counter", "rtpu_collective_ops_total",
    "Collective calls completed by this rank")


def _observe(op: str, group: str, nbytes: int, t0: float) -> None:
    tags = (("group", group), ("op", op))
    telemetry.counter_inc(M_COLL_OPS, 1.0, tags)
    if nbytes:
        telemetry.counter_inc(M_COLL_BYTES, float(nbytes), tags)
    telemetry.hist_observe(M_COLL_LATENCY, time.monotonic() - t0, tags)

# ops
SUM = "sum"
PROD = "prod"
MIN = "min"
MAX = "max"

_REDUCERS = {
    SUM: lambda arrs: np.sum(arrs, axis=0),
    PROD: lambda arrs: np.prod(arrs, axis=0),
    MIN: lambda arrs: np.min(arrs, axis=0),
    MAX: lambda arrs: np.max(arrs, axis=0),
}


@remote(num_cpus=0)
class _Coordinator:
    """Rendezvous + reduction point for one collective group.

    Each collective call is identified by (op_kind, seq). Members post
    contributions; the call completes when world_size contributions have
    arrived. Sequence numbers are tracked per member so reuse across
    repeated calls is safe.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._calls: Dict[tuple, dict] = {}
        self._mailbox: Dict[tuple, Any] = {}

    def _call(self, key):
        rec = self._calls.get(key)
        if rec is None:
            rec = {"parts": {}, "result": None, "done": False}
            self._calls[key] = rec
        return rec

    def contribute(self, key, rank: int, value) -> None:
        rec = self._call(key)
        rec["parts"][rank] = value

    def poll(self, key, op: Optional[str]):
        """Returns (done, result). Computes the reduction exactly once."""
        rec = self._call(key)
        if rec["done"]:
            return True, rec["result"]
        if len(rec["parts"]) < self.world_size:
            return False, None
        parts = [rec["parts"][r] for r in range(self.world_size)]
        if op is None:            # allgather / barrier: list of parts
            rec["result"] = parts
        else:
            stacked = np.stack([np.asarray(p) for p in parts])
            # keep the contribution dtype: np.sum promotes int32->int64,
            # but collectives contract to return what was put in (NCCL
            # semantics)
            rec["result"] = _REDUCERS[op](stacked).astype(
                stacked.dtype, copy=False)
        rec["done"] = True
        rec["acks"] = set()
        return True, rec["result"]

    def ack(self, key, rank: int) -> None:
        rec = self._calls.get(key)
        if rec is None:
            return
        rec.setdefault("acks", set()).add(rank)
        if len(rec["acks"]) >= self.world_size:
            del self._calls[key]

    def post(self, dst_rank: int, tag, value) -> None:
        self._mailbox[(dst_rank, tag)] = value

    def take(self, dst_rank: int, tag):
        if (dst_rank, tag) in self._mailbox:
            return True, self._mailbox.pop((dst_rank, tag))
        return False, None


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, coordinator):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.seq = 0
        # p2p sequence counters keyed by (peer_rank, tag)
        self.send_seq: Dict[tuple, int] = {}
        self.recv_seq: Dict[tuple, int] = {}


# Per-process registry (module-global like the reference's GroupManager,
# ``collective.py:40``; actor methods may run on different threads).
_process_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()


def _groups() -> Dict[str, _GroupState]:
    return _process_groups


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join a collective group (reference: ``collective.py:120``).

    Call from every member actor/task with a distinct ``rank``. Rank 0
    creates the named coordinator actor; others look it up.
    """
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    actor_name = _GROUP_ACTOR_PREFIX + group_name
    coordinator = None
    if rank == 0:
        coordinator = _Coordinator.options(name=actor_name).remote(world_size)
        # touch it so registration completes before others look it up
        get(coordinator.take.remote(-1, "warmup"))
    else:
        deadline = time.monotonic() + 30.0
        while True:
            try:
                coordinator = get_actor(actor_name)
                break
            except ValueError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"collective group {group_name!r}: coordinator "
                        "never appeared (is rank 0 up?)")
                time.sleep(0.02)
    with _groups_lock:
        _process_groups[group_name] = _GroupState(group_name, world_size,
                                                  rank, coordinator)


class CollectiveActorMixin:
    """Mix into an actor class to make it driveable by
    ``create_collective_group`` (and get convenience methods)."""

    def _rtpu_init_collective(self, world_size: int, rank: int,
                              group_name: str) -> None:
        init_collective_group(world_size, rank, group_name)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int],
                            group_name: str = "default") -> None:
    """Driver-side declarative setup (reference: ``collective.py:177``):
    instructs each actor to call ``init_collective_group``. Actor classes
    must inherit ``CollectiveActorMixin`` (or expose an equivalent
    ``_rtpu_init_collective`` method).

    Rank 0's init creates the coordinator and later ranks block on its
    appearance, so all members are driven concurrently here.
    """
    if len(actors) != world_size or len(ranks) != world_size:
        raise ValueError(
            f"need exactly world_size={world_size} actors and ranks, got "
            f"{len(actors)} actors / {len(ranks)} ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size-1}, "
                         f"got {ranks}")
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor._rtpu_init_collective.remote(world_size, rank,
                                                       group_name))
    get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        state = _process_groups.pop(group_name, None)
    if state is not None and state.rank == 0:
        from .. import kill
        try:
            kill(state.coordinator)
        except Exception:
            pass


def get_rank(group_name: str = "default") -> int:
    state = _groups().get(group_name)
    return -1 if state is None else state.rank


def get_collective_group_size(group_name: str = "default") -> int:
    state = _groups().get(group_name)
    return -1 if state is None else state.world_size


def _state(group_name: str) -> _GroupState:
    state = _groups().get(group_name)
    if state is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            "process; call init_collective_group first")
    return state


def _rendezvous(state: _GroupState, kind: str, payload, op: Optional[str],
                timeout: float = 60.0):
    key = (kind, state.seq)
    state.seq += 1
    get(state.coordinator.contribute.remote(key, state.rank, payload))
    deadline = time.monotonic() + timeout
    delay = 0.001
    while True:
        done, result = get(state.coordinator.poll.remote(key, op))
        if done:
            state.coordinator.ack.remote(key, state.rank)
            return result
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"collective {kind} in group {state.name!r} timed out "
                f"(rank {state.rank})")
        time.sleep(delay)
        delay = min(delay * 2, 0.05)


def _to_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


def allreduce(tensor, group_name: str = "default", op: str = SUM):
    """All-reduce; returns the reduced array (reference mutates in place —
    functional style here, jax arrays are immutable)."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    # Large payloads ride the object store; the coordinator sees refs
    # transparently because args are resolved at task execution.
    result = _rendezvous(state, "allreduce", put(arr), op)
    _observe("allreduce", group_name, arr.nbytes, t0)
    return result


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    parts = _rendezvous(state, "allgather", put(arr), None)
    _observe("allgather", group_name, arr.nbytes, t0)
    return [np.asarray(p) for p in parts]


def reducescatter(tensor, group_name: str = "default", op: str = SUM):
    """Reduce then return this rank's 1/world_size slice along axis 0."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    reduced = np.asarray(_rendezvous(state, "reducescatter",
                                     put(arr), op))
    _observe("reducescatter", group_name, arr.nbytes, t0)
    if reduced.shape[0] % state.world_size:
        raise ValueError(
            f"reducescatter: leading dim {reduced.shape[0]} not divisible "
            f"by world size {state.world_size}")
    chunk = reduced.shape[0] // state.world_size
    return reduced[state.rank * chunk:(state.rank + 1) * chunk]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    payload = put(arr) if state.rank == src_rank else None
    parts = _rendezvous(state, "broadcast", payload, None)
    _observe("broadcast", group_name,
             arr.nbytes if state.rank == src_rank else 0, t0)
    return np.asarray(parts[src_rank])


def barrier(group_name: str = "default") -> None:
    state = _state(group_name)
    t0 = time.monotonic()
    _rendezvous(state, "barrier", None, None)
    _observe("barrier", group_name, 0, t0)


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    state = _state(group_name)
    seq = state.send_seq.get((dst_rank, tag), 0)
    state.send_seq[(dst_rank, tag)] = seq + 1
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    get(state.coordinator.post.remote(
        dst_rank, (state.rank, tag, seq), put(arr)))
    _observe("send", group_name, arr.nbytes, t0)


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = 60.0):
    state = _state(group_name)
    seq = state.recv_seq.get((src_rank, tag), 0)
    state.recv_seq[(src_rank, tag)] = seq + 1
    t0 = time.monotonic()
    deadline = time.monotonic() + timeout
    delay = 0.001
    while True:
        ok, value = get(state.coordinator.take.remote(
            state.rank, (src_rank, tag, seq)))
        if ok:
            arr = np.asarray(value)
            _observe("recv", group_name, arr.nbytes, t0)
            return arr
        if time.monotonic() > deadline:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        time.sleep(delay)
        delay = min(delay * 2, 0.05)
