"""Host-level collective communication: peer-to-peer pipelined rings.

API mirrors the reference's ``util/collective/collective.py:258-615``
(allreduce/allgather/reducescatter/broadcast/send/recv/barrier, group
init by world_size+rank+group_name). Where the reference backs these
with NCCL/Gloo process groups, here the data plane is the node-plane
zero-copy transport (``_private/coll_transport.py``): ranks exchange
tensor chunks peer to peer as out-of-band pickle-5 iovecs, and
completion is driven by connection reader threads waking condition
variables — no polling anywhere on the data path.

Algorithms (reference model: "The Big Send-off" / bandwidth-optimal
collective schedules, EQuARX block-quantized allreduce):

- **ring allreduce** = reduce-scatter + allgather over the rank ring,
  tensors split into ``collective_chunk_bytes`` chunks so chunk k+1
  transmits while chunk k reduces; per-rank wire traffic is
  ~2x tensor size, independent of world size.
- **ring reduce-scatter / allgather** reuse the two ring phases.
- **binomial-tree broadcast** (chunk-pipelined down the tree) and a
  small-payload **tree allreduce** below
  ``collective_tree_threshold_bytes`` (latency-bound regime: 2·log2(w)
  hops beat a 2·(w-1)-step ring).
- **hierarchical two-level schedules** on multi-node groups with
  co-located ranks: intra-node reduce to one elected leader per node
  (those hops ride the same-host fast path) -> inter-node ring among
  the leaders only -> intra-node broadcast, so cross-wire traffic is
  ~1/ranks-per-node of a flat ring's.
- **block-quantized wire format** (``collective_wire_dtype`` = exact |
  bf16 | int8-blockscale): inter-node hops of hierarchical REDUCTIONS
  dequantize -> reduce -> requantize per hop, trading bounded
  max-abs error for 2-4x wire reduction; intra-node hops and ops that
  relay caller bytes verbatim (broadcast/allgather/send/recv) always
  stay exact, and the reduce order stays deterministic, so every rank
  still returns bit-identical bytes.
- **send/recv** are direct rank-to-rank mailbox messages.

Every public op picks its schedule through ONE table —
``_select_schedule(op, nbytes, world, nodes, dtype)`` — overridable
with ``collective_algo``; choices are observable via
``rtpu_collective_algo_total{algo,op}``.

The named ``_Coordinator`` actor is control plane only: group
membership, rank -> endpoint exchange, epoch agreement — plus a
degenerate fallback data path (``collective_p2p_enabled=False`` or a
rank with no runtime endpoint) that reduces by streaming pairwise
accumulation on waiter futures (O(size) peak memory, no polling).

**Self-healing** (ISSUE 12 / ROADMAP item 6): a call that fails with a
flight-recorder ``dead_rank`` verdict can recover instead of killing
the group — survivors fence the failing epoch
(``coll_transport.fence``), re-join through the coordinator's reform
round under a fresh epoch (``collective_reform_mode`` = replace |
shrink), and the fault-tolerant wrappers (``ft_allreduce`` /
``FaultTolerantGroup`` / ``ft_collective``) re-issue the failed op.
Restarted checkpointable actors re-enter with their old rank via
``ensure_collective_group``. See DESIGN.md "Collective self-healing".
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import exceptions, get, get_actor
from ..api import remote
from .._private import coll_transport
from .._private import failpoints
from .._private import flight_recorder
from .._private import locksan
from .._private import telemetry
from .._private.config import CONFIG

_GROUP_ACTOR_PREFIX = "rtpu:collective:"

M_COLL_LATENCY = telemetry.define(
    "histogram", "rtpu_collective_latency_seconds",
    "End-to-end latency of one host-level collective call, tagged by "
    "op and group (the communication axis)")
M_COLL_BYTES = telemetry.define(
    "counter", "rtpu_collective_bytes_total",
    "Payload bytes contributed to collectives by this rank")
M_COLL_OPS = telemetry.define(
    "counter", "rtpu_collective_ops_total",
    "Collective calls completed by this rank")
M_COLL_ALGO = telemetry.define(
    "counter", "rtpu_collective_algo_total",
    "Collective calls by the schedule the size x topology x dtype "
    "selector chose (ring/tree/hierarchical/star/local) — makes the "
    "crossover points observable")
M_COLL_QUANT_SAVED = telemetry.define(
    "counter", "rtpu_collective_quantized_bytes_total",
    "Wire bytes SAVED by the block-quantized inter-node format "
    "(original minus encoded payload bytes, summed over quantized hops)")
M_COLL_TIMEOUTS = telemetry.define(
    "counter", "rtpu_collective_timeouts_total",
    "Collective calls that failed with a TimeoutError on this rank "
    "(each one triggers the flight-recorder hang diagnosis)")
M_COLL_REFORMS = telemetry.define(
    "counter", "rtpu_collective_reforms_total",
    "Collective group reforms this rank adopted (a fresh epoch after a "
    "dead-rank verdict), tagged by the reform mode that resolved the "
    "round — replace (a restarted rank re-entered) or shrink (the "
    "world contracted to the survivors)")


class CollectiveTimeoutError(TimeoutError):
    """A collective call's deadline passed. Carries the flight
    recorder's cluster-wide diagnosis so recovery code can act on the
    VERDICT instead of string-matching the message: ``verdicts`` is the
    list of verdict dicts for this group (``dead_rank`` is the one the
    fault-tolerant wrappers reform on)."""

    def __init__(self, message: str, group: str = "",
                 verdicts: Optional[List[dict]] = None):
        super().__init__(message)
        self.group = group
        self.verdicts = list(verdicts or ())

    def dead_ranks(self) -> List[int]:
        return [v["rank"] for v in self.verdicts
                if v.get("verdict") == "dead_rank"]


def _observe(op: str, group: str, nbytes: int, t0: float) -> None:
    tags = (("group", group), ("op", op))
    telemetry.counter_inc(M_COLL_OPS, 1.0, tags)
    if nbytes:
        telemetry.counter_inc(M_COLL_BYTES, float(nbytes), tags)
    telemetry.hist_observe(M_COLL_LATENCY, time.monotonic() - t0, tags)


def _observe_algo(op: str, algo: str) -> None:
    telemetry.counter_inc(M_COLL_ALGO, 1.0, (("algo", algo), ("op", op)))

# ops
SUM = "sum"
PROD = "prod"
MIN = "min"
MAX = "max"

# binary ufuncs: streaming pairwise accumulation keeps peak memory at
# O(size) (the seed's np.stack over world_size arrays was O(world*size))
# and, unlike np.sum's axis reduction, never promotes the dtype
_BINARY = {SUM: np.add, PROD: np.multiply, MIN: np.minimum, MAX: np.maximum}


# ------------------------------------------- block-quantized wire format
#
# EQuARX-style precision/bandwidth trade on the hops that actually cross
# a wire: inter-node legs of hierarchical REDUCTIONS encode each chunk
# to bf16 or per-block-scaled int8 before it enters the transport's OOB
# frames, and the receiving rank thread dequantizes after the mailbox
# wait (reader threads stay lean — rule 4 of the threading model). Ops
# that relay caller bytes verbatim (broadcast/allgather/send/recv) and
# every intra-node hop are never quantized.

_WIRE_DTYPES = ("exact", "bf16", "int8-blockscale")


class QuantChunk:
    """Wire form of one quantized chunk — self-describing, so a receiver
    needs no schedule context to decode. ``q`` (the bf16 bit pattern or
    the int8 mantissas) rides out-of-band like plain ndarray chunks;
    ``scales`` is None for bf16. ``dtype`` is the ORIGINAL dtype the
    decoder restores (reduction then proceeds in that dtype, keeping
    the deterministic reduce order of the exact schedules)."""

    __slots__ = ("mode", "dtype", "q", "scales")

    def __init__(self, mode: str, dtype: str, q, scales=None):
        self.mode = mode
        self.dtype = dtype
        self.q = q
        self.scales = scales

    @property
    def nbytes(self) -> int:
        # also consulted by the transport's _est_size so chunk bursts
        # don't over-coalesce into one giant BATCH frame
        n = int(self.q.nbytes)
        if self.scales is not None:
            n += int(self.scales.nbytes)
        return n


def _bf16_encode(x32: np.ndarray) -> np.ndarray:
    """float32 -> bfloat16 bit pattern (uint16), round-to-nearest-even
    (numpy has no native bfloat16; the bit trick is exact)."""
    u = x32.view(np.uint32)
    return (((u + 0x7FFF + ((u >> 16) & 1)) >> 16)).astype(np.uint16)


def _bf16_decode(u16: np.ndarray) -> np.ndarray:
    return (u16.astype(np.uint32) << 16).view(np.float32)


def _q8_block_counts(n: int, block: int) -> Tuple[np.ndarray, np.ndarray]:
    idx = np.arange(0, n, block, dtype=np.int64)
    counts = np.full(idx.size, block, dtype=np.int64)
    counts[-1] = n - idx[-1]
    return idx, counts


class _WireCodec:
    """Encoder/decoder for the inter-node hops of one collective call.

    ``encode`` is the identity for exact mode, non-float dtypes
    (integer reductions must stay exact) and empty chunks; ``decode``
    of a plain ndarray is the identity, so exact and quantized traffic
    can share one schedule. ``saved`` accumulates original-minus-
    encoded bytes for the wire-savings counter."""

    def __init__(self, mode: str, block: int):
        if mode not in _WIRE_DTYPES:
            raise ValueError(
                f"collective_wire_dtype must be one of {_WIRE_DTYPES}, "
                f"got {mode!r}")
        self.mode = mode
        self.block = max(1, int(block))
        self.saved = 0

    @property
    def active(self) -> bool:
        return self.mode != "exact"

    def encode(self, arr):
        arr = np.ascontiguousarray(arr)
        if not self.active or arr.dtype.kind != "f" or arr.size == 0:
            return arr
        x32 = np.ascontiguousarray(
            arr.astype(np.float32, copy=False).reshape(-1))
        if not np.isfinite(x32).all():
            # non-finite values don't survive either format (an inf
            # poisons its whole int8 block's scale to NaN, NaN rounds
            # to 0, negative-NaN bit patterns wrap the bf16 add): ship
            # this chunk exact so a diverging gradient propagates
            # faithfully instead of being silently masked
            return arr
        if self.mode == "bf16":
            out = QuantChunk("bf16", arr.dtype.str, _bf16_encode(x32))
        else:
            idx, counts = _q8_block_counts(x32.size, self.block)
            absmax = np.maximum.reduceat(np.abs(x32), idx)
            scales = (absmax / 127.0).astype(np.float32)
            # an all-zero block quantizes through scale 1 (q is all 0);
            # the stored scale keeps the true value so decode stays 0
            safe = np.where(scales > 0, scales, np.float32(1.0))
            q = np.clip(np.rint(x32 / np.repeat(safe, counts)),
                        -127, 127).astype(np.int8)
            out = QuantChunk("int8-blockscale", arr.dtype.str, q, scales)
        self.saved += max(0, int(arr.nbytes) - out.nbytes)
        return out

    def decode(self, payload) -> np.ndarray:
        if not isinstance(payload, QuantChunk):
            return np.asarray(payload)
        if payload.mode == "bf16":
            x32 = _bf16_decode(payload.q)
        else:
            _idx, counts = _q8_block_counts(payload.q.size, self.block)
            safe = np.where(payload.scales > 0, payload.scales,
                            np.float32(1.0))
            x32 = payload.q.astype(np.float32) * np.repeat(safe, counts)
        return x32.astype(np.dtype(payload.dtype), copy=False)

    def roundtrip(self, arr: np.ndarray) -> np.ndarray:
        """encode -> decode without sending: a segment's OWNER must end
        up holding exactly the bytes every receiver will decode, or the
        ranks diverge bit-wise."""
        if not self.active:
            return arr
        return self.decode(self.encode(arr))


def _make_codec() -> _WireCodec:
    return _WireCodec(CONFIG.collective_wire_dtype,
                      CONFIG.collective_quant_block_elems)


def _observe_quant(codec: Optional[_WireCodec], op: str,
                   group: str) -> None:
    if codec is not None and codec.saved:
        telemetry.counter_inc(M_COLL_QUANT_SAVED, float(codec.saved),
                              (("group", group), ("op", op)))


# ------------------------------------------------- algorithm selection

_ALGO_CHOICES = ("auto", "ring", "tree", "hierarchical", "star")

# which schedules each op can run; a forced/selected algo outside the
# mask degrades to the op's bandwidth schedule (barrier has no payload,
# so topology never matters to it)
_ALGO_CAPS = {
    "allreduce": ("ring", "tree", "hierarchical", "star"),
    "reducescatter": ("ring", "hierarchical", "star"),
    "allgather": ("ring", "hierarchical", "star"),
    "broadcast": ("tree", "hierarchical", "star"),
    "barrier": ("tree", "star"),
}


def _select_schedule(op: str, nbytes: int, world: int, nodes: int,
                     dtype) -> str:
    """The size x topology x dtype selection table. Pure function of
    its arguments plus CONFIG (``collective_algo`` forces a schedule,
    ``collective_tree_threshold_bytes`` and
    ``collective_hierarchical_threshold_bytes`` set the crossovers).

    - latency-bound sizes (below the tree threshold) -> binomial tree;
    - multi-node topologies with co-located ranks (world > nodes > 1)
      and bandwidth-bound sizes -> hierarchical two-level (the
      threshold halves for float payloads when a quantized wire dtype
      is configured: cheaper inter-node bytes amortize the intra-node
      staging hops sooner);
    - everything else -> flat ring (broadcast's bandwidth schedule is
      the chunk-pipelined tree).
    """
    caps = _ALGO_CAPS[op]
    fallback = "ring" if "ring" in caps else "tree"
    forced = CONFIG.collective_algo
    if forced != "auto":
        if forced not in _ALGO_CHOICES:
            raise ValueError(
                f"collective_algo must be one of {_ALGO_CHOICES}, "
                f"got {forced!r}")
        return forced if forced in caps else fallback
    if op == "barrier":
        return "tree"
    multi_node = nodes > 1 and world > nodes
    if op in ("allgather", "broadcast"):
        # topology-only: per-rank payload sizes may differ (allgather)
        # or be unknown off-source (broadcast), and every rank MUST
        # derive the same schedule from the same shared data — a
        # size-keyed rule would let ranks diverge and deadlock
        return "hierarchical" if multi_node else fallback
    if nbytes < CONFIG.collective_tree_threshold_bytes and "tree" in caps:
        return "tree"
    if "hierarchical" in caps and multi_node:
        threshold = CONFIG.collective_hierarchical_threshold_bytes
        if (CONFIG.collective_wire_dtype != "exact"
                and getattr(dtype, "kind", "") == "f"):
            threshold //= 2
        if nbytes >= threshold:
            return "hierarchical"
    return fallback


class _CoordinatorImpl:
    """Control plane of one collective group (async actor).

    Owns membership (rank -> endpoint exchange under a fresh group
    epoch) and the degenerate fallback data path. Every blocking call
    awaits an ``asyncio.Event`` resolved by the completing member —
    callers block on the actor reply, never on a poll loop. Call
    records a timed-out rank abandoned (and mailbox posts never taken)
    are swept once they outlive ``ttl_s``.
    """

    def __init__(self, world_size: int, ttl_s: Optional[float] = None):
        self.world_size = world_size
        self.epoch = os.urandom(8).hex()
        self.ttl_s = float(ttl_s if ttl_s is not None
                           else CONFIG.collective_call_ttl_s)
        self._endpoints: Dict[int, Any] = {}
        self._join_ev = asyncio.Event()
        self._calls: Dict[tuple, dict] = {}
        self._mail: Dict[tuple, tuple] = {}          # key -> (value, born)
        self._mail_evs: Dict[tuple, asyncio.Event] = {}
        # reform state: at most one open round (superseding self.epoch)
        # plus a bounded cache of resolved rounds keyed by the epoch
        # they superseded, so a slow survivor that calls reform() after
        # the round resolved still adopts the same result. Once any
        # round RENUMBERS ranks (a shrink that dropped members), old
        # rank ids stop naming members — re-entry by stale rank id is
        # refused from then on.
        self._reform: Optional[dict] = None
        self._reform_results: Dict[str, dict] = {}
        self._renumbered = False
        # set when a SURVIVOR of an established epoch talks to this
        # (freshly restarted, empty) coordinator: the group exists even
        # though no join ever ran here — join-delegation must stop, but
        # _join_ev must NOT be set (that would wake parked joiners into
        # a partial, endpoint-less membership)
        self._established = False

    def ping(self) -> bool:
        return True

    def debug_counts(self) -> Dict[str, int]:
        """Test surface: live fallback-call records and mailbox posts."""
        self._sweep()
        return {"calls": len(self._calls), "mail": len(self._mail)}

    def _sweep(self) -> None:
        """Drop records older than the TTL: a rank that timed out of a
        rendezvous leaves a partial record behind, and an un-taken post
        has no reader — neither may live forever."""
        now = time.monotonic()
        for key, rec in list(self._calls.items()):
            if now - rec["born"] > self.ttl_s:
                rec["expired"] = True
                rec["ev"].set()
                del self._calls[key]
        for key, (_value, born) in list(self._mail.items()):
            if now - born > self.ttl_s:
                del self._mail[key]

    # ------------------------------------------------------- membership
    async def join(self, rank: int, endpoint, timeout_s: float):
        """Register this rank's endpoint; resolves for everyone once
        all world_size ranks arrived. Returns (epoch, endpoints)."""
        self._endpoints[rank] = (tuple(endpoint) if endpoint is not None
                                 else None)
        if len(self._endpoints) >= self.world_size:
            self._join_ev.set()
        else:
            try:
                await asyncio.wait_for(self._join_ev.wait(), timeout_s)
            except asyncio.TimeoutError:
                missing = [r for r in range(self.world_size)
                           if r not in self._endpoints]
                return ("timeout",
                        f"ranks {missing} never joined the group")
        eps = [self._endpoints.get(r) for r in range(self.world_size)]
        return ("ok", (self.epoch, eps))

    # ------------------------------------------------------------ reform
    #
    # Self-healing membership: after a dead-rank verdict, every survivor
    # fences the failing epoch locally and calls ``reform``; a restarted
    # replacement rank calls it too (``from_epoch`` None — it has no
    # process state). The round resolves under a FRESH epoch either when
    # all world_size ranks re-arrived (``replace`` — the restarted rank
    # re-enters with its old rank) or, in ``shrink`` mode, once no new
    # rank has arrived for ``grace_s`` — the world contracts to the
    # survivors, renumbered contiguously in old-rank order. Stale
    # fallback-path records and mail are cleared at resolution (their
    # keys don't all carry the epoch — this IS their fence).

    async def reform(self, rank: int, endpoint, from_epoch: Optional[str],
                     mode: str, timeout_s: float, grace_s: float,
                     world: Optional[int] = None):
        """Join the reform round superseding ``from_epoch`` (None = the
        current epoch, for ranks whose process state died with them).
        ``world`` is the CALLER's view of the group size — a restarted
        (empty) coordinator adopts it from the first surviving caller,
        since its __init__ args may predate shrink reforms. Returns
        ("ok", {epoch, world, rank, endpoints, reformed})."""
        cached = (self._reform_results.get(from_epoch)
                  if from_epoch is not None else None)
        if cached is not None:
            return self._reform_reply(cached, rank)
        if (from_epoch is not None and not self._established
                and not self._join_ev.is_set()):
            # a survivor of an ESTABLISHED epoch is talking to a
            # freshly restarted coordinator: the group exists — don't
            # fall into the initial-join path (whose world may be the
            # pre-shrink __init__ value); adopt the survivor's view.
            # NOT via _join_ev: setting that would wake a parked
            # joiner (a restarted rank that raced ahead of us) into a
            # partial, endpoint-less membership — it must instead time
            # out of its join and retry into the round below.
            if world:
                self.world_size = int(world)
            self._established = True
        if from_epoch is None and (self._renumbered
                                   or rank >= self.world_size):
            # a restarted rank re-entering AFTER a shrink round
            # renumbered the members: its OLD rank id either fell off
            # the end or now aliases a renumbered survivor — admitting
            # it would put two processes behind one rank's mailbox keys
            return ("timeout",
                    f"rank {rank} is not a member of the current group "
                    f"(world {self.world_size}; ranks were renumbered "
                    "by a shrink reform); re-initialize or restart the "
                    "whole group to re-admit it")
        if not self._join_ev.is_set() and not self._established:
            # initial formation still open: a (re-)joiner is a joiner —
            # this also covers a RESTARTED coordinator (empty state):
            # every rank's idempotent re-join rebuilds membership and
            # resolves under this incarnation's fresh epoch
            status, res = await self.join(rank, endpoint, timeout_s)
            if status != "ok":
                return (status, res)
            epoch, eps = res
            return ("ok", {"epoch": epoch, "world": self.world_size,
                           "rank": rank, "endpoints": eps,
                           "reformed": False})
        rec = self._reform
        if rec is None:
            rec = self._reform = {
                "arrived": {}, "mode": mode, "from_epoch": self.epoch,
                "last_arrival": time.monotonic(), "result": None,
                "survivor_seen": False, "ev": asyncio.Event()}
        if rank not in rec["arrived"]:
            rec["last_arrival"] = time.monotonic()
        # latest arrival's mode wins: a round opened in replace mode
        # that timed out (the replacement never came) must honor a
        # retry made after the operator switched to shrink — freezing
        # the opener's mode would make the advertised escape hatch
        # ("set collective_reform_mode=shrink") a no-op
        rec["mode"] = mode
        if from_epoch is not None:
            # a SURVIVOR (it names the epoch it watched fail) is in the
            # round: only then may shrink-quiescence resolve it. A lone
            # restarted rank (from_epoch None) waiting for survivors
            # that haven't failed yet must never shrink the live group
            # down to a world of itself.
            rec["survivor_seen"] = True
        rec["arrived"][rank] = (tuple(endpoint) if endpoint is not None
                                else None)
        if len(rec["arrived"]) >= self.world_size:
            self._resolve_reform(rec)
        rec["waiters"] = rec.get("waiters", 0) + 1
        try:
            deadline = time.monotonic() + timeout_s
            while rec["result"] is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    missing = sorted(set(range(self.world_size))
                                     - set(rec["arrived"]))
                    return ("timeout",
                            f"group reform: ranks {missing} never "
                            f"re-joined within {timeout_s:.0f}s "
                            "(replace mode waits for a restarted "
                            "replacement rank; set "
                            "collective_reform_mode=shrink to proceed "
                            "without them)")
                wait = remaining
                if rec["mode"] == "shrink" and rec["survivor_seen"]:
                    # grace runs from the LAST arrival: a trickle of
                    # stragglers keeps the round open, quiescence
                    # closes it
                    grace_left = (rec["last_arrival"] + grace_s
                                  - time.monotonic())
                    if grace_left <= 0:
                        self._resolve_reform(rec)
                        break
                    wait = min(wait, grace_left)
                try:
                    await asyncio.wait_for(rec["ev"].wait(), wait)
                except asyncio.TimeoutError:
                    pass
            return self._reform_reply(rec["result"], rank)
        finally:
            rec["waiters"] -= 1
            if (rec["waiters"] <= 0 and rec["result"] is None
                    and self._reform is rec):
                # every waiter abandoned an unresolved round: discard
                # it — its arrivals are stale endpoints, and a later
                # lone re-joiner must not inherit its survivor_seen
                # flag and shrink the live group around ghost members
                self._reform = None

    def _resolve_reform(self, rec: dict) -> None:
        if rec["result"] is not None:
            return
        old_ranks = sorted(rec["arrived"])
        result = {"epoch": os.urandom(8).hex(), "world": len(old_ranks),
                  "ranks": {old: new for new, old in enumerate(old_ranks)},
                  "endpoints": [rec["arrived"][o] for o in old_ranks],
                  "reformed": True}
        rec["result"] = result
        self._reform_results[rec["from_epoch"]] = result
        while len(self._reform_results) > 8:
            self._reform_results.pop(next(iter(self._reform_results)))
        if any(old != new for old, new in result["ranks"].items()):
            self._renumbered = True
        self.epoch = result["epoch"]
        self.world_size = result["world"]
        self._endpoints = {new: rec["arrived"][old]
                           for old, new in result["ranks"].items()}
        # fence the fallback data path: rendezvous records and mailbox
        # posts of the superseded epoch must never satisfy a new-epoch
        # call (mail keys don't carry the epoch — clearing here is
        # their only fence)
        self._calls.clear()
        self._mail.clear()
        self._reform = None
        rec["ev"].set()

    @staticmethod
    def _reform_reply(result: dict, rank: int):
        new_rank = result["ranks"].get(rank)
        if new_rank is None:
            return ("timeout",
                    f"rank {rank} is not a member of the reformed group "
                    "(it missed the shrink-mode round); re-initialize "
                    "or restart the whole group to re-admit it")
        return ("ok", {"epoch": result["epoch"],
                       "world": result["world"], "rank": new_rank,
                       "endpoints": result["endpoints"],
                       "reformed": True})

    # ------------------------------------------- fallback data path
    def _call(self, key) -> dict:
        rec = self._calls.get(key)
        if rec is None:
            rec = {"count": 0, "acc": None, "parts": {}, "result": None,
                   "done": False, "taken": 0, "expired": False,
                   "born": time.monotonic(), "ev": asyncio.Event()}
            self._calls[key] = rec
        return rec

    async def rendezvous(self, key, rank: int, value, op: Optional[str],
                         timeout_s: float):
        """Blocking rendezvous: contribution ``world_size`` resolves the
        waiters. ``op`` None gathers parts (allgather/broadcast/barrier);
        otherwise the reduction accumulates pairwise as values arrive."""
        self._sweep()
        rec = self._call(key)
        if op is None:
            # copy: the deserialized view may alias a store segment that
            # is unpinned once this call returns
            rec["parts"][rank] = (np.array(value)
                                  if isinstance(value, np.ndarray)
                                  else value)
        else:
            v = np.asarray(value)
            rec["acc"] = (np.array(v) if rec["acc"] is None
                          else _BINARY[op](rec["acc"], v))
        rec["count"] += 1
        if rec["count"] >= self.world_size:
            rec["result"] = (rec["acc"] if op is not None else
                             [rec["parts"].get(r)
                              for r in range(self.world_size)])
            rec["done"] = True
            rec["ev"].set()
        elif not rec["done"]:
            try:
                await asyncio.wait_for(rec["ev"].wait(), timeout_s)
            except asyncio.TimeoutError:
                # leave the partial record for the TTL sweep
                return ("timeout",
                        f"{rec['count']}/{self.world_size} ranks arrived")
        if rec["expired"]:
            return ("timeout", "call record expired (TTL sweep)")
        rec["taken"] += 1
        if rec["taken"] >= self.world_size:
            self._calls.pop(key, None)
        return ("ok", rec["result"])

    async def post(self, dst_rank: int, tag, value) -> None:
        self._sweep()
        key = (dst_rank, tuple(tag))
        self._mail[key] = (np.array(value)
                           if isinstance(value, np.ndarray) else value,
                           time.monotonic())
        ev = self._mail_evs.get(key)
        if ev is not None:
            ev.set()

    async def take(self, dst_rank: int, tag, timeout_s: float):
        self._sweep()
        key = (dst_rank, tuple(tag))
        if key not in self._mail:
            ev = self._mail_evs.get(key)
            if ev is None:
                ev = self._mail_evs[key] = asyncio.Event()
            try:
                await asyncio.wait_for(ev.wait(), timeout_s)
            except asyncio.TimeoutError:
                return ("timeout", f"no message for tag {tag}")
            finally:
                self._mail_evs.pop(key, None)
        if key not in self._mail:            # raced the TTL sweep
            return ("timeout", "message expired (TTL sweep)")
        value, _born = self._mail.pop(key)
        return ("ok", value)


# Restart budget: a SIGKILLed/OOM-killed coordinator comes back (same
# actor id, fresh empty state) and the idempotent re-join paths rebuild
# membership under its new epoch — joiners retry on ActorDiedError
# instead of stranding until the collective timeout (see _coord_call).
_COORDINATOR_MAX_RESTARTS = 3
_Coordinator = remote(
    num_cpus=0, max_restarts=_COORDINATOR_MAX_RESTARTS)(_CoordinatorImpl)


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, coordinator,
                 epoch: str, endpoints: List[Any]):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.epoch = epoch
        self.endpoints = endpoints
        # p2p only when every rank published a routable endpoint (all
        # ranks derive this from the same exchanged data, so the whole
        # group agrees on the schedule)
        self.use_p2p = all(ep is not None for ep in endpoints)
        # ------ topology: endpoints carry node identity (endpoint[0] is
        # the owning node's id), so every rank derives the SAME node
        # grouping from the same exchanged data — the hierarchical
        # schedules route on it with no extra control-plane round trip
        self.nodes: List[Any] = []            # node ids, first-rank order
        self.node_ranks: Dict[Any, List[int]] = {}
        if self.use_p2p:
            for r, ep in enumerate(endpoints):
                nid = ep[0]
                if nid not in self.node_ranks:
                    self.nodes.append(nid)
                    self.node_ranks[nid] = []
                self.node_ranks[nid].append(r)
        self.n_nodes = len(self.nodes) if self.use_p2p else 1
        if self.use_p2p:
            my_node = endpoints[rank][0]
            self.local_ranks = self.node_ranks[my_node]   # sorted (scan)
            self.leader = self.local_ranks[0]
            self.leaders = [self.node_ranks[nid][0] for nid in self.nodes]
        else:
            self.local_ranks = [rank]
            self.leader = rank
            self.leaders = [rank]
        # node blocks are contiguous iff concatenating each node's ranks
        # in node order counts 0..w-1 — the precondition for the
        # hierarchical reduce-scatter's per-node segment bounds
        self.node_blocks_contiguous = (
            self.use_p2p
            and sum((self.node_ranks[nid] for nid in self.nodes), [])
            == list(range(world_size)))
        self.seq = 0
        # p2p sequence counters keyed by (peer_rank, tag)
        self.send_seq: Dict[tuple, int] = {}
        self.recv_seq: Dict[tuple, int] = {}

    def next_seq(self) -> int:
        seq = self.seq
        self.seq += 1
        return seq

    def key(self, seq: int) -> tuple:
        return (self.name, self.epoch, seq)


class _SubState:
    """A sub-group view the ring/tree schedule helpers run on unchanged:
    ``members`` (global ranks, same order on every rank — derived from
    the shared endpoint exchange) are remapped to 0..len-1. Used for the
    per-node gang and the leaders-only ring of hierarchical schedules;
    key disambiguation is the caller's job (distinct key prefixes per
    phase, and phase-1/3 messages only ever travel between co-located
    ranks, so equal local indices on different nodes cannot collide)."""

    def __init__(self, state: _GroupState, members: List[int]):
        self.name = state.name
        self.members = members
        self.world_size = len(members)
        self.rank = members.index(state.rank)
        self.endpoints = [state.endpoints[g] for g in members]


# Per-process registry (module-global like the reference's GroupManager,
# ``collective.py:40``; actor methods may run on different threads).
_process_groups: Dict[str, _GroupState] = {}
_groups_lock = locksan.lock("collective.groups")


def _groups() -> Dict[str, _GroupState]:
    return _process_groups


def _coord(state_or_actor, method: str, *args):
    """Call a coordinator method and unwrap its ("ok"|"timeout", x)
    status tuple; "timeout" raises here so every rank surfaces it. A
    dead coordinator surfaces as a clear 'coordinator died' error, not
    a bare actor failure."""
    try:
        res = get(getattr(state_or_actor, method).remote(*args))
    except exceptions.ActorDiedError as exc:
        raise RuntimeError(
            f"collective coordinator actor died mid-{method} (restart "
            f"budget exhausted or killed): {exc}") from exc
    if res[0] != "ok":
        raise TimeoutError(f"collective {method}: {res[1]}")
    return res[1]


def _coord_call(actor, group_name: str, method: str, *args,
                retries: int = _COORDINATOR_MAX_RESTARTS):
    """``_coord`` for the IDEMPOTENT membership ops (join/reform): an
    in-flight call that dies with the coordinator's worker is simply
    re-issued — the restarted coordinator (same actor id, empty state)
    collects the re-joins afresh and resolves under its new epoch. Only
    when the restart budget is exhausted (the actor stays DEAD) does
    the caller get the terminal 'coordinator died' error."""
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            res = get(getattr(actor, method).remote(*args))
        except exceptions.ActorDiedError as exc:
            last = exc
            time.sleep(0.1 * (attempt + 1))
            continue
        if res[0] != "ok":
            raise TimeoutError(f"collective {method}: {res[1]}")
        return res[1]
    raise RuntimeError(
        f"collective group {group_name!r}: coordinator actor died and "
        f"its restart budget ({_COORDINATOR_MAX_RESTARTS}) is exhausted "
        f"— {method} cannot complete: {last}")


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join a collective group (reference: ``collective.py:120``).

    Call from every member actor/task with a distinct ``rank``. Rank 0
    creates the named coordinator actor; others look it up. All members
    then exchange (rank -> endpoint) through the coordinator, which is
    what the peer-to-peer ring/tree schedules route on.
    """
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    actor_name = _GROUP_ACTOR_PREFIX + group_name
    coordinator = None
    if rank == 0:
        coordinator = _Coordinator.options(name=actor_name).remote(world_size)
        # touch it so registration completes before others look it up
        get(coordinator.ping.remote())
    else:
        deadline = time.monotonic() + 30.0
        while True:                 # control plane (init only): the
            try:                    # data path never polls
                coordinator = get_actor(actor_name)
                break
            except ValueError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"collective group {group_name!r}: coordinator "
                        "never appeared (is rank 0 up?)")
                time.sleep(0.02)
    ep = (coll_transport.local_endpoint()
          if CONFIG.collective_p2p_enabled else None)
    # join is idempotent: a coordinator death mid-join fails every
    # blocked joiner at once, and every one of them re-joins the
    # restarted (empty) coordinator — _coord_call owns the retry
    epoch, endpoints = _coord_call(coordinator, group_name, "join",
                                   rank, ep, CONFIG.collective_timeout_s)
    flight_recorder.register_group(group_name, epoch, rank, world_size,
                                   endpoints)
    with _groups_lock:
        _process_groups[group_name] = _GroupState(
            group_name, world_size, rank, coordinator, epoch, endpoints)


class CollectiveActorMixin:
    """Mix into an actor class to make it driveable by
    ``create_collective_group`` (and get convenience methods)."""

    def _rtpu_init_collective(self, world_size: int, rank: int,
                              group_name: str) -> None:
        init_collective_group(world_size, rank, group_name)

    def _rtpu_destroy_collective(self, group_name: str) -> None:
        destroy_collective_group(group_name)

    def _rtpu_ensure_collective(self, world_size: int, rank: int,
                                group_name: str) -> None:
        """Idempotent (re-)join — what a restarted checkpointable rank
        calls at the top of its step to re-enter with its old rank."""
        ensure_collective_group(world_size, rank, group_name)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int],
                            group_name: str = "default") -> None:
    """Driver-side declarative setup (reference: ``collective.py:177``):
    instructs each actor to call ``init_collective_group``. Actor classes
    must inherit ``CollectiveActorMixin`` (or expose an equivalent
    ``_rtpu_init_collective`` method).

    Rank 0's init creates the coordinator and later ranks block on its
    appearance, so all members are driven concurrently here.
    """
    if len(actors) != world_size or len(ranks) != world_size:
        raise ValueError(
            f"need exactly world_size={world_size} actors and ranks, got "
            f"{len(actors)} actors / {len(ranks)} ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size-1}, "
                         f"got {ranks}")
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor._rtpu_init_collective.remote(world_size, rank,
                                                       group_name))
    get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    """GROUP-WIDE teardown (call it from every member, like the
    reference's destroy): the shared coordinator dies with the FIRST
    member's destroy, so this is not a single-rank 'leave' — a member
    that destroys while others still use the group takes their control
    plane with it. Bounded even when a rank (including rank 0) is
    dead: the epoch is fenced — the dead member's stranded mailbox
    chunks are swept now, late stale arrivals refused — and every
    member attempts the coordinator kill (the first wins; killing a
    dead actor, or a PREVIOUS group's coordinator after a same-name
    recreate, no-ops — the kill targets this group's actor id, not the
    name). Rank 0 used to be the only killer, so a group whose rank 0
    died leaked its named coordinator forever and the name could never
    be reused."""
    with _groups_lock:
        state = _process_groups.pop(group_name, None)
    if state is None:
        return
    flight_recorder.unregister_group(state.name, state.epoch)
    # fence subsumes the old drop_group sweep: it deletes the epoch's
    # undelivered chunks AND refuses late arrivals
    coll_transport.fence(state.name, state.epoch)
    from .. import kill
    try:
        kill(state.coordinator)
    except Exception:
        pass


# ------------------------------------------------- self-healing reform
#
# The detect -> recover loop (ROADMAP item 6): a collective that fails
# with a flight-recorder dead_rank verdict no longer just reports — the
# survivors fence the failing epoch, re-exchange endpoints through the
# coordinator under a fresh epoch (waiting for a restarted replacement
# rank, or shrinking the world, per ``collective_reform_mode``), and the
# fault-tolerant wrappers re-issue the failed op on the reformed group.

def ensure_collective_group(world_size: int, rank: int,
                            group_name: str = "default") -> None:
    """Idempotent (re-)join. A process that already holds live group
    state no-ops (reforms it participated in kept it current); a FRESH
    process — typically a restarted checkpointable actor — re-enters
    the group's open reform round with its old ``rank``, unblocking the
    survivors parked in replace-mode reform. Falls back to
    ``init_collective_group`` when the coordinator doesn't exist yet
    (first formation)."""
    if _groups().get(group_name) is not None:
        return
    actor_name = _GROUP_ACTOR_PREFIX + group_name
    try:
        coordinator = get_actor(actor_name)
    except ValueError:
        init_collective_group(world_size, rank, group_name)
        return
    failpoints.fp("coll.reform.join", group=group_name, rank=rank)
    ep = (coll_transport.local_endpoint()
          if CONFIG.collective_p2p_enabled else None)
    res = _coord_call(coordinator, group_name, "reform", rank, ep, None,
                      _reform_mode(), CONFIG.collective_reform_timeout_s,
                      CONFIG.collective_reform_grace_s, world_size)
    _adopt_membership(group_name, coordinator, res, _reform_mode(),
                      "restarted rank re-entry")


def _reform_mode() -> str:
    mode = CONFIG.collective_reform_mode
    if mode not in ("replace", "shrink"):
        raise ValueError(
            f"collective_reform_mode must be 'replace' or 'shrink', "
            f"got {mode!r}")
    return mode


def reform_collective_group(group_name: str = "default",
                            reason: str = "",
                            timeout: Optional[float] = None) -> int:
    """Re-form this group under a fresh epoch after a rank death.

    Fences the current (failing) epoch FIRST — from that instant no
    chunk of it can enter this process's mailbox — then joins the
    coordinator's reform round. In ``replace`` mode the round resolves
    once all world_size ranks re-arrived (a restarted rank re-enters
    with the same rank via ``ensure_collective_group``); in ``shrink``
    mode it resolves once arrivals quiesce for
    ``collective_reform_grace_s`` and the world contracts to the
    survivors. Returns this rank's rank in the reformed group."""
    with _groups_lock:
        state = _process_groups.get(group_name)
    if state is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            "process; nothing to reform")
    mode = _reform_mode()
    coll_transport.fence(state.name, state.epoch)
    failpoints.fp("coll.reform.join", group=group_name, rank=state.rank)
    ep = (coll_transport.local_endpoint()
          if CONFIG.collective_p2p_enabled else None)
    t = timeout if timeout is not None else CONFIG.collective_reform_timeout_s
    res = _coord_call(state.coordinator, group_name, "reform",
                      state.rank, ep, state.epoch, mode, t,
                      CONFIG.collective_reform_grace_s, state.world_size)
    ns = _adopt_membership(group_name, state.coordinator, res, mode,
                           reason)
    return ns.rank


def _adopt_membership(group_name: str, coordinator, res: dict,
                      mode: str, reason: str) -> _GroupState:
    """Install a reform round's result as this process's group state:
    retire the old epoch everywhere (recorder registry, mailbox), build
    the new ``_GroupState``, and account the reform (metric + one
    COLLECTIVE_REFORM event, emitted by the new rank 0)."""
    endpoints = [tuple(e) if e is not None else None
                 for e in res["endpoints"]]
    epoch, world, rank = res["epoch"], res["world"], res["rank"]
    with _groups_lock:
        old = _process_groups.get(group_name)
    if old is not None and old.epoch != epoch:
        flight_recorder.unregister_group(group_name, old.epoch)
        # fence (not just sweep): a manual reform call that skipped
        # reform_collective_group's own fence still closes the epoch
        coll_transport.fence(group_name, old.epoch)
    flight_recorder.register_group(group_name, epoch, rank, world,
                                   endpoints)
    ns = _GroupState(group_name, world, rank, coordinator, epoch,
                     endpoints)
    with _groups_lock:
        _process_groups[group_name] = ns
    if res.get("reformed"):
        telemetry.counter_inc(M_COLL_REFORMS, 1.0,
                              (("group", group_name), ("mode", mode)))
        if rank == 0:
            _emit_reform_event(group_name, epoch, mode, world, reason)
    return ns


def _emit_reform_event(group_name: str, epoch: str, mode: str,
                       world: int, reason: str) -> None:
    """Ship one COLLECTIVE_REFORM event through this process's node
    (the node's EventLogger owns the literal emit — reforms happen in
    worker/driver rank processes that have no logger of their own)."""
    from .._private import context
    client = context.current_client
    if client is None:
        return
    try:
        client.send_profile_event("coll_reform", {
            "message": (f"collective group {group_name!r} reformed "
                        f"under epoch {epoch[:8]} (mode={mode}, "
                        f"world={world})"
                        + (f": {reason}" if reason else "")),
            "group": group_name, "epoch": epoch, "mode": mode,
            "world": world, "reason": reason})
    except Exception:   # noqa: BLE001 — accounting must not fail recovery
        pass


def _reformable(exc: BaseException) -> List[dict]:
    return [v for v in getattr(exc, "verdicts", ())
            if v.get("verdict") == "dead_rank"]


class FaultTolerantGroup:
    """Retrying view of one collective group: each op re-issues after an
    automatic group reform when (and only when) its TimeoutError carries
    a flight-recorder ``dead_rank`` verdict — a merely slow rank keeps
    its group. Bounded: ``retries`` reforms per call (default
    ``collective_reform_retries``) with exponential backoff between
    re-issues. All member ranks must drive their ops through the same
    wrapper so every survivor enters the same reform round."""

    def __init__(self, group_name: str = "default",
                 retries: Optional[int] = None,
                 timeout: Optional[float] = None):
        self.group_name = group_name
        self.retries = (retries if retries is not None
                        else CONFIG.collective_reform_retries)
        self.timeout = timeout

    def _run(self, fn, *args, rank_sensitive: bool = False, **kwargs):
        kwargs.setdefault("timeout", self.timeout)
        attempt = 0
        while True:
            try:
                return fn(*args, group_name=self.group_name, **kwargs)
            except TimeoutError as exc:
                dead = _reformable(exc)
                if not dead or attempt >= self.retries:
                    if dead:
                        # the reform budget is exhausted with a dead-rank
                        # verdict standing: this call is terminal for the
                        # training loop — leave a black-box bundle the
                        # operator can autopsy offline
                        from ray_tpu._private import debug_bundle
                        debug_bundle.auto_capture(
                            "collective_reform_exhausted",
                            fields={"group": self.group_name,
                                    "verdict": dead[0].get("message",
                                                           "dead rank")})
                    raise
                attempt += 1
                before = _groups().get(self.group_name)
                old = (before.world_size, before.rank) if before else None
                reform_collective_group(
                    self.group_name,
                    reason=dead[0].get("message", "dead rank"))
                after = _groups().get(self.group_name)
                if (rank_sensitive and after is not None
                        and old != (after.world_size, after.rank)):
                    # the reform RENUMBERED ranks (shrink dropped a
                    # member): the caller's rank-addressed arguments
                    # (broadcast src, reducescatter slices) now name
                    # different physical members — silently re-issuing
                    # would complete with the WRONG member's data
                    raise RuntimeError(
                        f"collective group {self.group_name!r} shrank "
                        f"during reform (world {old[0] if old else '?'}"
                        f" -> {after.world_size}, ranks renumbered): "
                        f"cannot safely re-issue the rank-addressed "
                        f"{fn.__name__} — re-issue it with ranks from "
                        "the reformed group") from exc
                time.sleep(min(0.25 * (2 ** (attempt - 1)), 2.0))

    def allreduce(self, tensor, op: str = SUM):
        return self._run(allreduce, tensor, op=op)

    def allgather(self, tensor):
        return self._run(allgather, tensor)

    def reducescatter(self, tensor, op: str = SUM):
        # output slices are addressed by rank: safe to re-issue only
        # while the reform preserved this rank's identity (replace)
        return self._run(reducescatter, tensor, op=op,
                         rank_sensitive=True)

    def broadcast(self, tensor, src_rank: int = 0):
        return self._run(broadcast, tensor, src_rank=src_rank,
                         rank_sensitive=True)

    def barrier(self):
        return self._run(barrier)


def ft_allreduce(tensor, group_name: str = "default", op: str = SUM,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None):
    """``allreduce`` with automatic dead-rank recovery: on a
    ``dead_rank`` verdict the group reforms under a fresh epoch (see
    ``reform_collective_group``) and the op re-issues, up to
    ``retries`` times. The workhorse of a fault-tolerant training
    step."""
    return FaultTolerantGroup(group_name, retries=retries,
                              timeout=timeout).allreduce(tensor, op=op)


@contextlib.contextmanager
def ft_collective(group_name: str = "default",
                  retries: Optional[int] = None,
                  timeout: Optional[float] = None):
    """Context manager yielding a :class:`FaultTolerantGroup`::

        with ft_collective("train", timeout=5.0) as grp:
            out = grp.allreduce(grads)
    """
    yield FaultTolerantGroup(group_name, retries=retries, timeout=timeout)


def get_rank(group_name: str = "default") -> int:
    state = _groups().get(group_name)
    return -1 if state is None else state.rank


def get_collective_group_size(group_name: str = "default") -> int:
    state = _groups().get(group_name)
    return -1 if state is None else state.world_size


def _state(group_name: str) -> _GroupState:
    state = _groups().get(group_name)
    if state is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            "process; call init_collective_group first")
    return state


def _to_numpy(tensor) -> np.ndarray:
    """Ingest a caller tensor as a C-CONTIGUOUS ndarray. The schedules
    ship zero-copy views of this array: pickle-5 only exports
    C-contiguous buffers out-of-band, so a transposed/strided input
    would silently fall back to an in-band copy whose byte order no
    longer matches the flat C-order reshape the receivers perform.
    ``ascontiguousarray`` is a no-copy view for already-contiguous
    input (the common case)."""
    return np.ascontiguousarray(np.asarray(tensor))


def _deadline(timeout: Optional[float]) -> float:
    return time.monotonic() + (timeout if timeout is not None
                               else CONFIG.collective_timeout_s)


def _timeout_s(timeout: Optional[float]) -> float:
    return timeout if timeout is not None else CONFIG.collective_timeout_s


# --------------------------------------------------------- ring schedules
#
# Ring convention (delta = -1): at reduce-scatter step s, rank r sends
# segment (r-1-s) mod w and receives segment (r-2-s) mod w from its left
# neighbor, reducing it into the local buffer — after w-1 steps rank r
# holds segment r fully reduced. The allgather phase then circulates the
# finished segments the same way. Chunks pipeline: a chunk is forwarded
# the moment it is reduced, so chunk k+1 is on the wire while chunk k
# reduces, and a chunk's buffer is never mutated again until the data
# derived from it has causally passed through the next rank (which makes
# the zero-copy views safe).

def _chunk_ranges(a: int, b: int, chunk_elems: int) -> List[Tuple[int, int]]:
    out = []
    while a < b:
        e = min(a + chunk_elems, b)
        out.append((a, e))
        a = e
    return out


def _chunk_elems(dtype) -> int:
    return max(1, CONFIG.collective_chunk_bytes // max(1, dtype.itemsize))


def _send(state: _GroupState, dst_rank: int, key: tuple, payload,
          op: str) -> None:
    coll_transport.send(state.endpoints[dst_rank], key, payload,
                        group=state.name, op=op)


def _ring_reduce_scatter(state, buf: np.ndarray,
                         bounds: List[int], op: str, key: tuple,
                         deadline: float, opname: str,
                         codec: Optional[_WireCodec] = None) -> None:
    """In-place ring reduce-scatter over ``buf`` segments ``bounds``;
    on return segment ``rank`` holds the full reduction. With a
    ``codec`` every hop is encoded before the send and decoded before
    the reduce (dequantize -> reduce -> requantize: the reduce itself
    always runs in the original dtype, in ring order — deterministic)."""
    w, r = state.world_size, state.rank
    right = (r + 1) % w
    ce = _chunk_elems(buf.dtype)
    binop = _BINARY[op]
    enc = codec.encode if codec is not None else (lambda x: x)
    dec = codec.decode if codec is not None else np.asarray

    def chunks(seg: int) -> List[Tuple[int, int]]:
        return _chunk_ranges(bounds[seg], bounds[seg + 1], ce)

    first = (r - 1) % w
    for ci, (a, b) in enumerate(chunks(first)):
        _send(state, right, key + ("rs", first, ci), enc(buf[a:b]), opname)
    for s in range(w - 1):
        seg = (r - 2 - s) % w
        for ci, (a, b) in enumerate(chunks(seg)):
            data = coll_transport.wait(key + ("rs", seg, ci), deadline)
            failpoints.fp("coll.ring.rs_hop", rank=r, step=s, seg=seg,
                          chunk=ci, seq=key[2])
            view = buf[a:b]
            binop(view, dec(data), out=view)
            if s < w - 2:
                # forward the just-reduced chunk while the next chunk
                # of this segment is still in flight (pipelining)
                _send(state, right, key + ("rs", seg, ci), enc(view),
                      opname)


def _ring_allgather_segments(state, buf: np.ndarray,
                             bounds: List[int], key: tuple,
                             deadline: float, opname: str,
                             codec: Optional[_WireCodec] = None) -> None:
    """Ring allgather of ``buf`` segments: each rank starts with its own
    segment final (post reduce-scatter) and circulates; on return every
    segment of ``buf`` is final. With a ``codec`` each segment is
    encoded ONCE by its owner, forwarded verbatim, and the owner writes
    the encode->decode roundtrip back into its own segment — so every
    rank decodes (and returns) bit-identical bytes."""
    w, r = state.world_size, state.rank
    right = (r + 1) % w
    ce = _chunk_elems(buf.dtype)
    dec = codec.decode if codec is not None else np.asarray

    def chunks(seg: int) -> List[Tuple[int, int]]:
        return _chunk_ranges(bounds[seg], bounds[seg + 1], ce)

    for ci, (a, b) in enumerate(chunks(r)):
        if codec is not None and codec.active:
            enc = codec.encode(buf[a:b])
            _send(state, right, key + ("ag", r, ci), enc, opname)
            buf[a:b] = codec.decode(enc)
        else:
            _send(state, right, key + ("ag", r, ci), buf[a:b], opname)
    for s in range(w - 1):
        seg = (r - 1 - s) % w
        for ci, (a, b) in enumerate(chunks(seg)):
            data = coll_transport.wait(key + ("ag", seg, ci), deadline)
            if s < w - 2:
                # forward the received (zero-copy) payload untouched —
                # quantized segments are never re-encoded in flight
                _send(state, right, key + ("ag", seg, ci), data, opname)
            buf[a:b] = dec(data)


# --------------------------------------------------------- tree schedules

def _tree_parent_children(v: int, w: int) -> Tuple[Optional[int], List[int]]:
    """Binomial tree rooted at virtual rank 0: parent clears v's lowest
    set bit; children are v + m for descending m below it."""
    if v == 0:
        lsb = 1
        while lsb < w:
            lsb <<= 1
        parent = None
    else:
        lsb = v & -v
        parent = v - lsb
    children = []
    m = lsb >> 1
    while m:
        if v + m < w:
            children.append(v + m)
        m >>= 1
    return parent, children


def _tree_reduce(state: _GroupState, arr: np.ndarray, op: str, key: tuple,
                 deadline: float, opname: str) -> Optional[np.ndarray]:
    """Binomial-tree reduction to rank 0; returns the total at rank 0,
    None elsewhere (small payloads: whole arrays per hop)."""
    w, r = state.world_size, state.rank
    binop = _BINARY[op]
    acc = np.array(arr)
    mask = 1
    while mask < w:
        if r & mask:
            _send(state, r - mask, key + ("tr", r), acc, opname)
            return None
        peer = r | mask
        if peer < w:
            data = coll_transport.wait(key + ("tr", peer), deadline)
            acc = binop(acc, np.asarray(data))
        mask <<= 1
    return acc


def _tree_bcast_small(state: _GroupState, data, src_rank: int, key: tuple,
                      deadline: float, opname: str) -> np.ndarray:
    """Whole-payload binomial broadcast (small/known-shape payloads)."""
    w, r = state.world_size, state.rank
    v = (r - src_rank) % w
    parent, children = _tree_parent_children(v, w)
    if parent is not None:
        data = coll_transport.wait(key + ("tb", v), deadline)
    for c in children:
        _send(state, (c + src_rank) % w, key + ("tb", c), data, opname)
    return np.asarray(data)


def _tree_bcast_chunked(state: _GroupState, value: Optional[np.ndarray],
                        src_rank: int, key: tuple, deadline: float,
                        opname: str) -> np.ndarray:
    """Chunk-pipelined binomial broadcast: non-source ranks learn the
    shape from a header, then each chunk is forwarded down the tree the
    moment it arrives (chunk k+1 rides the wire while k lands)."""
    w, r = state.world_size, state.rank
    v = (r - src_rank) % w
    parent, children = _tree_parent_children(v, w)

    def fanout(subkey: tuple, payload) -> None:
        for c in children:
            _send(state, (c + src_rank) % w, key + subkey + (c,), payload,
                  opname)

    if parent is None:
        flat = np.ascontiguousarray(value).reshape(-1)
        ranges = _chunk_ranges(0, flat.size, _chunk_elems(flat.dtype))
        header = (value.shape, flat.dtype.str, len(ranges))
        fanout(("bh",), header)
        for ci, (a, b) in enumerate(ranges):
            fanout(("bc", ci), flat[a:b])
        return np.asarray(value)
    shape, dtype_str, nchunks = coll_transport.wait(
        key + ("bh", v), deadline)
    fanout(("bh",), (shape, dtype_str, nchunks))
    buf = np.empty(int(np.prod(shape, dtype=np.int64)),
                   dtype=np.dtype(dtype_str))
    pos = 0
    for ci in range(nchunks):
        data = coll_transport.wait(key + ("bc", ci, v), deadline)
        fanout(("bc", ci), data)
        arr = np.asarray(data)
        buf[pos:pos + arr.size] = arr
        pos += arr.size
    return buf.reshape(tuple(shape))


# -------------------------------------------------- hierarchical schedules
#
# Two-level topology-aware schedules ("The Big Send-off" intra-node ->
# inter-node shape): ranks are grouped by the node id their endpoint
# carries, the lowest rank on each node is its leader, and only leaders
# speak across nodes. On an m-node group with k ranks per node the
# inter-node traffic of an allreduce drops from a flat ring's ~2x size
# per CROSSING EDGE (of which there are m) to ~2·(m-1)/m·size per
# LEADER — i.e. ~1/k of the total cross-wire bytes — and the intra-node
# staging hops ride the same-host fast path. The optional wire codec
# applies ONLY to the leader-ring hops of reductions.

def _hier_allreduce(state: _GroupState, buf: np.ndarray, op: str,
                    key: tuple, deadline: float, opname: str,
                    codec: Optional[_WireCodec]) -> np.ndarray:
    """allreduce = intra-node binomial reduce to the leader ->
    leaders-only ring allreduce (codec on the hops) -> intra-node
    binomial broadcast — fused per OUTER CHUNK so the three phases
    pipeline: while the leaders run the inter-node ring on chunk k,
    chunk k+1 is already climbing the local tree and chunk k-1 is
    fanning back out (sends are fire-and-forget, so a member's phase-1
    send of one chunk never waits on the ring). Serial critical path is
    ~one phase's bytes, not the sum of all three. Returns the flat
    result (aliasing ``buf`` on leaders)."""
    local = _SubState(state, state.local_ranks)
    lv, lw = local.rank, local.world_size
    parent, children = _tree_parent_children(lv, lw)
    is_leader = parent is None
    leaders = (_SubState(state, state.leaders)
               if is_leader and state.n_nodes > 1 else None)
    ranges = _chunk_ranges(0, buf.size, _chunk_elems(buf.dtype))
    binop = _BINARY[op]
    out = buf if is_leader else np.empty_like(buf)
    for ci, (a, b) in enumerate(ranges):
        view = buf[a:b]
        # phase 1: this chunk climbs the local binomial tree (children
        # reduce into us, we pass the partial up)
        for c in children:
            data = coll_transport.wait(key + ("hl", ci, c), deadline)
            binop(view, np.asarray(data), out=view)
        if not is_leader:
            # failpoint BEFORE the send: a chaos kill at chunk k dies
            # with chunk k-1 already in flight but chunk k never sent,
            # so the survivors wedge inside THIS op (and the whole step
            # retries aligned after the reform) instead of completing
            # without the victim and skewing one step ahead of it
            failpoints.fp("coll.hier.phase", phase="up", rank=state.rank,
                          chunk=ci, seq=key[2])
            _send(state, local.members[parent], key + ("hl", ci, lv),
                  view, opname)
            continue
        # phase 2 (leader): inter-node ring allreduce of this chunk
        if leaders is not None:
            m = leaders.world_size
            cb = [a + (i * (b - a)) // m for i in range(m + 1)]
            _ring_reduce_scatter(leaders, buf, cb, op, key + ("hx", ci),
                                 deadline, opname, codec=codec)
            _ring_allgather_segments(leaders, buf, cb, key + ("hx", ci),
                                     deadline, opname, codec=codec)
        # phase 3 (leader): fan the finished chunk down the local tree
        failpoints.fp("coll.hier.phase", phase="ring", rank=state.rank,
                      chunk=ci, seq=key[2])
        for c in children:
            _send(state, local.members[c], key + ("hb", ci, c), view,
                  opname)
    if not is_leader:
        # phase 3: chunks arrive from the parent, forward to our
        # subtree, assemble the result
        for ci, (a, b) in enumerate(ranges):
            data = coll_transport.wait(key + ("hb", ci, lv), deadline)
            for c in children:
                _send(state, local.members[c], key + ("hb", ci, c),
                      data, opname)
            out[a:b] = np.asarray(data)
    return out


def _hier_reducescatter(state: _GroupState, buf: np.ndarray, op: str,
                        seg_elems: int, key: tuple, deadline: float,
                        opname: str,
                        codec: Optional[_WireCodec]) -> np.ndarray:
    """reducescatter = intra-node tree reduce to the leader -> leaders
    ring reduce-scatter over PER-NODE segment blocks (codec on the
    hops) -> leader hands each co-located rank its slice. Requires
    ``state.node_blocks_contiguous`` (the selector's caller degrades to
    the flat ring otherwise). Returns this rank's flat slice."""
    r = state.rank
    local = _SubState(state, state.local_ranks)
    total = _tree_reduce(local, buf, op, key + ("hl",), deadline, opname)
    if total is not None:
        if state.n_nodes > 1:
            leaders = _SubState(state, state.leaders)
            # node j's block spans its member ranks' slices (contiguous
            # by precondition, in leader-ring segment order)
            bounds = [state.node_ranks[nid][0] * seg_elems
                      for nid in state.nodes]
            bounds.append(state.world_size * seg_elems)
            _ring_reduce_scatter(leaders, total, bounds, op,
                                 key + ("hx",), deadline, opname,
                                 codec=codec)
        for peer in state.local_ranks[1:]:
            a = peer * seg_elems
            _send(state, peer, key + ("hs", peer),
                  total[a:a + seg_elems], opname)
        return total[r * seg_elems:(r + 1) * seg_elems]
    data = coll_transport.wait(key + ("hs", r), deadline)
    return np.asarray(data).reshape(-1)


def _hier_allgather(state: _GroupState, arr: np.ndarray, key: tuple,
                    deadline: float, opname: str) -> List[np.ndarray]:
    """allgather = co-located ranks hand their arrays to the leader ->
    leaders ring-allgather per-node BUNDLES (one mailbox message per
    node per hop instead of one per rank) -> leader fans the full part
    list back out. Caller bytes are relayed verbatim (never quantized)."""
    w, r = state.world_size, state.rank
    if r != state.leader:
        _send(state, state.leader, key + ("hga", r), arr, opname)
        parts = coll_transport.wait(key + ("hgb", r), deadline)
        return [np.asarray(p) for p in parts]
    out: List[Any] = [None] * w
    out[r] = arr
    for peer in state.local_ranks[1:]:
        out[peer] = np.asarray(
            coll_transport.wait(key + ("hga", peer), deadline))
    if state.n_nodes > 1:
        leaders = _SubState(state, state.leaders)
        lr = leaders.rank
        m = leaders.world_size
        right = (lr + 1) % m
        my_nid = state.nodes[lr]
        bundle = tuple(out[g] for g in state.node_ranks[my_nid])
        _send(state, state.leaders[right], key + ("hgx", lr), bundle,
              opname)
        for s in range(m - 1):
            src = (lr - 1 - s) % m
            bundle = coll_transport.wait(key + ("hgx", src), deadline)
            if s < m - 2:
                _send(state, state.leaders[right], key + ("hgx", src),
                      bundle, opname)
            for g, part in zip(state.node_ranks[state.nodes[src]], bundle):
                out[g] = np.asarray(part)
    for peer in state.local_ranks[1:]:
        _send(state, peer, key + ("hgb", peer), tuple(out), opname)
    return [np.asarray(p) for p in out]


def _hier_broadcast(state: _GroupState, value: Optional[np.ndarray],
                    src_rank: int, key: tuple, deadline: float,
                    opname: str) -> np.ndarray:
    """broadcast = source -> its node's leader (one same-host hop) ->
    chunk-pipelined binomial tree over the LEADERS (every hop of it is
    a genuine cross-node transfer, m-1 of them — the minimum) ->
    chunk-pipelined tree inside each node. Bytes relayed verbatim."""
    r = state.rank
    src_node = state.endpoints[src_rank][0]
    src_leader = state.node_ranks[src_node][0]
    if r == src_rank and r != src_leader:
        _send(state, src_leader, key + ("hb0",), value, opname)
    data: Optional[np.ndarray] = value if r == src_rank else None
    if r in state.leaders:
        if r == src_leader and r != src_rank:
            data = np.asarray(
                coll_transport.wait(key + ("hb0",), deadline))
        leaders = _SubState(state, state.leaders)
        data = _tree_bcast_chunked(leaders, data,
                                   state.leaders.index(src_leader),
                                   key + ("hx",), deadline, opname)
    local = _SubState(state, state.local_ranks)
    out = _tree_bcast_chunked(local, data if r == state.leader else None,
                              0, key + ("hb",), deadline, opname)
    return np.asarray(out)


# ------------------------------------------------------------- public API

def _pick(state: _GroupState, op: str, nbytes: int, dtype) -> str:
    """Resolve the schedule for one call and record the choice (the
    counter must reflect the schedule that actually RUNS, so any
    topology-based demotion happens before recording)."""
    if state.world_size == 1:
        algo = "local"
    elif not state.use_p2p:
        algo = "star"
    else:
        algo = _select_schedule(op, nbytes, state.world_size,
                                state.n_nodes, dtype)
        if (algo == "hierarchical" and op == "reducescatter"
                and not state.node_blocks_contiguous):
            # per-node segment bounds need each node's ranks to span a
            # contiguous rank range; interleaved placements run the
            # flat ring
            algo = "ring"
    _observe_algo(op, algo)
    return algo


def _remote_verdict(state: _GroupState, okey) -> Tuple[str, List[dict]]:
    """Best-effort cluster-wide hang diagnosis after a local timeout:
    fan the COLL_PROGRESS query out through the control plane (answered
    on every process's reader thread — a peer wedged inside the same
    collective still replies), diff watermarks, and return (verdict
    sentence(s), verdict dicts) for this group/op. Empty when no
    runtime client is attached or the diagnosis itself fails. The
    dicts ride on the raised ``CollectiveTimeoutError`` so the
    fault-tolerant wrappers can reform on a dead_rank verdict without
    string-matching."""
    from .._private import context
    client = context.current_client
    if client is None or not flight_recorder.enabled():
        return "", []
    try:
        report = client.collective_health(
            CONFIG.coll_progress_timeout_s) or {}
    except Exception:   # noqa: BLE001 — diagnosis must not mask the error
        return "", []
    want = okey if isinstance(okey, int) else list(okey)
    verdicts = [v for v in report.get("verdicts", ())
                if v.get("group") == state.name and v.get("seq") == want]
    if not verdicts:
        verdicts = [v for v in report.get("verdicts", ())
                    if v.get("group") == state.name]
    return ("; ".join(v.get("message", "") for v in verdicts[:2]),
            verdicts)


def _run_op(state: _GroupState, op: str, algo: str, okey, nbytes: int,
            fn):
    """Run one public op's data path under the flight recorder.

    On success the op record retires into the recorder's completed ring
    (``state.timeline()`` renders those as spans). On a TimeoutError the
    failure is handled, not just raised: the timeout counter bumps, the
    cluster-wide diagnosis runs WHILE this rank's watermark record is
    still live (both survivors of a dead rank time out near-
    simultaneously — dropping the record first would blind the peer's
    diagnosis), the verdict is appended to the exception message, and
    the failed call's undelivered chunks are dropped from the mailbox so
    ``rtpu_collective_inflight_chunks`` returns to 0 now instead of at
    the TTL sweep."""
    flight_recorder.op_begin(state.name, state.epoch, okey, op, algo,
                             nbytes, state.world_size, state.rank)
    failpoints.fp("coll.op.begin", op=op, group=state.name,
                  rank=state.rank, seq=okey, algo=algo)
    try:
        out = fn()
    except TimeoutError as exc:
        telemetry.counter_inc(M_COLL_TIMEOUTS, 1.0,
                              (("group", state.name), ("op", op)))
        flight_recorder.op_error(state.name, okey, str(exc))
        detail, verdicts = _remote_verdict(state, okey)
        flight_recorder.op_end(state.name, okey)
        if isinstance(okey, int):
            # p2p send/recv awaited exactly one key that never arrived
            # — only sequenced schedule calls can strand delivered chunks
            coll_transport.drop_call(state.name, state.epoch, okey)
        msg = str(exc)
        if detail:
            msg = f"{msg} [diagnosis: {detail}]"
        raise CollectiveTimeoutError(msg, group=state.name,
                                     verdicts=verdicts) from None
    except BaseException as exc:
        # any other failure (dead coordinator actor, mismatched-shape
        # reduce, ...) must still retire the watermark record, or the
        # op reads as STUCK in every later health report
        flight_recorder.op_end(state.name, okey,
                               error=f"{type(exc).__name__}: {exc}")
        raise
    flight_recorder.op_end(state.name, okey)
    return out


def allreduce(tensor, group_name: str = "default", op: str = SUM,
              timeout: Optional[float] = None):
    """All-reduce; returns the reduced array (reference mutates in place —
    functional style here, jax arrays are immutable). Schedule per the
    selection table: binomial tree (latency-bound), flat ring, or
    hierarchical two-level (multi-node; optionally block-quantized
    inter-node). Every rank returns bit-identical bytes."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    algo = _pick(state, "allreduce", arr.nbytes, arr.dtype)

    def run():
        if algo == "local":
            return np.array(arr)
        if algo == "star":
            return np.asarray(_coord(state.coordinator, "rendezvous",
                                     state.key(seq), state.rank, arr, op,
                                     _timeout_s(timeout)))
        if algo == "tree":
            key, deadline = state.key(seq), _deadline(timeout)
            total = _tree_reduce(state, arr, op, key, deadline,
                                 "allreduce")
            result = _tree_bcast_small(state, total, 0, key, deadline,
                                       "allreduce").reshape(arr.shape)
            # the fanned-out buffer aliases the returned array (root) —
            # the caller may mutate it the moment we return, so the
            # zero-copy sends must have left this process first
            coll_transport.flush()
            return result
        if algo == "hierarchical":
            key, deadline = state.key(seq), _deadline(timeout)
            codec = _make_codec()
            buf = arr.reshape(-1).copy()
            out = _hier_allreduce(state, buf, op, key, deadline,
                                  "allreduce", codec)
            # leaders fan out zero-copy views of the result they return
            coll_transport.flush()
            _observe_quant(codec, "allreduce", group_name)
            return out.reshape(arr.shape)
        key, deadline = state.key(seq), _deadline(timeout)
        buf = arr.reshape(-1).copy()
        n = buf.size
        w = state.world_size
        bounds = [(i * n) // w for i in range(w + 1)]
        _ring_reduce_scatter(state, buf, bounds, op, key, deadline,
                             "allreduce")
        _ring_allgather_segments(state, buf, bounds, key, deadline,
                                 "allreduce")
        # allgather-phase sends are views of ``buf``, which the caller
        # receives (and may mutate) as the result — flush before return
        coll_transport.flush()
        return buf.reshape(arr.shape)

    result = _run_op(state, "allreduce", algo, seq, arr.nbytes, run)
    _observe("allreduce", group_name, arr.nbytes, t0)
    return result


def allgather(tensor, group_name: str = "default",
              timeout: Optional[float] = None) -> List[np.ndarray]:
    """Gather every rank's array (whole contributions circulate the
    ring; output is inherently O(world * size))."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    w, r = state.world_size, state.rank
    algo = _pick(state, "allgather", arr.nbytes, arr.dtype)

    def run():
        if algo == "local":
            return [np.array(arr)]
        if algo == "star":
            return [np.asarray(p) for p in _coord(
                state.coordinator, "rendezvous", state.key(seq), r, arr,
                None, _timeout_s(timeout))]
        if algo == "hierarchical":
            key, deadline = state.key(seq), _deadline(timeout)
            parts = _hier_allgather(state, arr, key, deadline,
                                    "allgather")
            # the caller's own ``arr`` (and, on leaders, the returned
            # parts) went out zero-copy — flush the link before they
            # can be mutated
            coll_transport.flush()
            return parts
        key, deadline = state.key(seq), _deadline(timeout)
        out: List[Any] = [None] * w
        out[r] = arr
        right = (r + 1) % w
        _send(state, right, key + ("ga", r), arr, "allgather")
        for s in range(w - 1):
            src = (r - 1 - s) % w
            data = coll_transport.wait(key + ("ga", src), deadline)
            if s < w - 2:
                _send(state, right, key + ("ga", src), data, "allgather")
            out[src] = np.asarray(data)
        # the caller's own ``arr`` went onto the ring zero-copy and the
        # caller may mutate it once we return — flush the link first
        coll_transport.flush()
        return [np.asarray(p) for p in out]

    parts = _run_op(state, "allgather", algo, seq, arr.nbytes, run)
    _observe("allgather", group_name, arr.nbytes, t0)
    return parts


def reducescatter(tensor, group_name: str = "default", op: str = SUM,
                  timeout: Optional[float] = None):
    """Reduce then return this rank's 1/world_size slice along axis 0
    (ring reduce-scatter: each rank receives only its own slice's
    traffic, ~1x tensor size per rank)."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    w, r = state.world_size, state.rank
    if arr.ndim == 0 or arr.shape[0] % w:
        raise ValueError(
            f"reducescatter: leading dim {arr.shape[:1]} not divisible "
            f"by world size {w}")
    rows = arr.shape[0] // w
    algo = _pick(state, "reducescatter", arr.nbytes, arr.dtype)

    def run():
        if algo == "local":
            return np.array(arr)
        if algo == "star":
            reduced = np.asarray(_coord(state.coordinator, "rendezvous",
                                        state.key(seq), r, arr, op,
                                        _timeout_s(timeout)))
            return reduced[r * rows:(r + 1) * rows]
        if algo == "hierarchical":
            key, deadline = state.key(seq), _deadline(timeout)
            codec = _make_codec()
            buf = arr.reshape(-1).copy()
            seg_elems = rows * (buf.size // arr.shape[0])
            out = _hier_reducescatter(state, buf, op, seg_elems, key,
                                      deadline, "reducescatter", codec)
            # leaders ship zero-copy slices of the buffer they keep a
            # slice of — flush before the caller can mutate the result
            coll_transport.flush()
            _observe_quant(codec, "reducescatter", group_name)
            return out.reshape((rows,) + arr.shape[1:]).copy()
        key, deadline = state.key(seq), _deadline(timeout)
        buf = arr.reshape(-1).copy()
        seg_elems = rows * (buf.size // arr.shape[0])
        bounds = [i * seg_elems for i in range(w + 1)]
        _ring_reduce_scatter(state, buf, bounds, op, key, deadline,
                             "reducescatter")
        return buf[bounds[r]:bounds[r + 1]].reshape(
            (rows,) + arr.shape[1:]).copy()

    result = _run_op(state, "reducescatter", algo, seq, arr.nbytes, run)
    _observe("reducescatter", group_name, arr.nbytes, t0)
    return result


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: Optional[float] = None):
    """Binomial-tree broadcast from ``src_rank``, chunk-pipelined down
    the tree; non-source ranks' tensors are ignored (shape/dtype arrive
    in the header)."""
    state = _state(group_name)
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    seq = state.next_seq()
    is_src = state.rank == src_rank
    algo = _pick(state, "broadcast", arr.nbytes if is_src else 0,
                 arr.dtype)

    def run():
        if algo == "local":
            return np.array(arr)
        if algo == "star":
            parts = _coord(state.coordinator, "rendezvous",
                           state.key(seq), state.rank,
                           arr if is_src else None, None,
                           _timeout_s(timeout))
            return np.asarray(parts[src_rank])
        if algo == "hierarchical":
            result = _hier_broadcast(state, arr if is_src else None,
                                     src_rank, state.key(seq),
                                     _deadline(timeout), "broadcast")
            coll_transport.flush()
            return result
        result = _tree_bcast_chunked(state, arr if is_src else None,
                                     src_rank, state.key(seq),
                                     _deadline(timeout), "broadcast")
        # the source fans out zero-copy views of the caller's tensor
        # (contiguous input: ascontiguousarray is a no-copy) — it must
        # be on the wire before the caller can touch it again
        coll_transport.flush()
        return result

    result = _run_op(state, "broadcast", algo, seq,
                     arr.nbytes if is_src else 0, run)
    _observe("broadcast", group_name, arr.nbytes if is_src else 0, t0)
    return result


def barrier(group_name: str = "default",
            timeout: Optional[float] = None) -> None:
    """All ranks block until every rank arrived (tree reduce + tree
    broadcast of an empty token — 2·log2(w) hops)."""
    state = _state(group_name)
    t0 = time.monotonic()
    seq = state.next_seq()
    algo = _pick(state, "barrier", 0, np.dtype(np.uint8))

    def run():
        if algo == "local":
            return None
        if algo == "star":
            _coord(state.coordinator, "rendezvous", state.key(seq),
                   state.rank, None, None, _timeout_s(timeout))
            return None
        key, deadline = state.key(seq), _deadline(timeout)
        token = np.zeros(1, dtype=np.uint8)
        total = _tree_reduce(state, token, SUM, key, deadline, "barrier")
        _tree_bcast_small(state, total, 0, key, deadline, "barrier")
        return None

    _run_op(state, "barrier", algo, seq, 0, run)
    _observe("barrier", group_name, 0, t0)


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    """Direct rank-to-rank send: one mailbox message straight to the
    destination rank's process (no coordinator hop)."""
    state = _state(group_name)
    seq = state.send_seq.get((dst_rank, tag), 0)
    state.send_seq[(dst_rank, tag)] = seq + 1
    arr = _to_numpy(tensor)
    t0 = time.monotonic()
    okey = ("p2p", state.rank, dst_rank, tag, seq)

    def run():
        if state.use_p2p:
            _send(state, dst_rank,
                  (state.name, state.epoch, "p2p", state.rank, dst_rank,
                   tag, seq), arr, "send")
            # ``arr`` aliases the caller's tensor (zero-copy); send()
            # must not return while it can still be pickled later by a
            # drainer
            coll_transport.flush()
        else:
            get(state.coordinator.post.remote(
                dst_rank, (state.rank, tag, seq), arr))
        return None

    _run_op(state, "send", "p2p" if state.use_p2p else "star", okey,
            arr.nbytes, run)
    _observe("send", group_name, arr.nbytes, t0)


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: Optional[float] = None):
    """Blocking receive of the matching ``send`` (FIFO per (src, tag));
    wakes on delivery, raises TimeoutError at the deadline."""
    state = _state(group_name)
    seq = state.recv_seq.get((src_rank, tag), 0)
    state.recv_seq[(src_rank, tag)] = seq + 1
    t0 = time.monotonic()
    okey = ("p2p", src_rank, state.rank, tag, seq)

    def run():
        if state.use_p2p:
            data = coll_transport.wait(
                (state.name, state.epoch, "p2p", src_rank, state.rank,
                 tag, seq), _deadline(timeout), what="p2p recv")
            return np.array(data)
        return np.asarray(_coord(state.coordinator, "take", state.rank,
                                 (src_rank, tag, seq),
                                 _timeout_s(timeout)))

    arr = _run_op(state, "recv", "p2p" if state.use_p2p else "star",
                  okey, 0, run)
    _observe("recv", group_name, arr.nbytes, t0)
    return arr
