"""ray_tpu.comm — the two communication planes (SURVEY §5, §7.7).

1. **In-program ICI collectives** — the default on TPU: psum/all_gather/
   ppermute inside jitted SPMD programs over a `jax.sharding.Mesh`
   (see ``ray_tpu.parallel``). There are no process groups to manage;
   XLA places the collectives. This replaces the reference's NCCL plane
   (``util/collective/collective_group/nccl_collective_group.py:127``).
2. **Host-level collectives** (this package): actor-to-actor
   allreduce/allgather/… for arrays that live on *hosts* (cross-slice
   DCN transfers, CPU rollout workers, parameter servers). API mirrors
   the reference's ``util/collective/collective.py:258-615``; rendezvous
   runs through a named actor like the reference's named-store rendezvous.

``MeshGroup`` ties a placement-group gang of host actors to one logical
device mesh — the SPMD-vs-actor bridge (SURVEY §7 "hard parts").
"""

from .collective import (  # noqa: F401
    CollectiveActorMixin,
    CollectiveTimeoutError,
    FaultTolerantGroup,
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    ensure_collective_group,
    ft_allreduce,
    ft_collective,
    get_rank,
    get_collective_group_size,
    init_collective_group,
    recv,
    reducescatter,
    reform_collective_group,
    send,
)
from .device_mesh import MeshGroup, mesh_group  # noqa: F401
