"""MeshGroup — the SPMD-vs-actor bridge (SURVEY §7 "hard parts").

On TPU, ONE jitted program owns all chips of a slice, but placement/
lifecycle is per *host* (4 chips per host). The reference has no
equivalent (its unit is one process per GPU with NCCL groups); here a
``MeshGroup`` is a placement-group gang of host actors driven in
lockstep: every ``run()`` invokes the same method on every host actor
concurrently, which is exactly the multi-controller JAX model
(`jax.distributed` — every host runs the same program, XLA runs the
collectives over ICI/DCN).

On a single-host dev box (or CPU tests) each actor simply owns the local
devices; the lockstep structure is identical, so code written against
MeshGroup moves to a real pod unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

from .. import get
from .collective import CollectiveActorMixin
from ..util.placement_group import (PlacementGroup, placement_group,
                                    remove_placement_group)
from ..util.scheduling_strategies import PlacementGroupSchedulingStrategy


class SPMDWorkerBase(CollectiveActorMixin):
    """Base for user host-actors in a MeshGroup.

    Subclasses get `self.mesh_rank` / `self.mesh_world` and can build a
    local `jax.sharding.Mesh` via `build_local_mesh()`. When the group
    was created with ``collective_group=...`` the host actors also form
    a host-level collective group (peer-to-peer ring/tree schedules over
    the node plane — see ``comm/collective.py``) and the ``mesh_*``
    helpers below run over it: host-side gradient/metric sync for the
    DCN axis, complementing the ICI collectives XLA runs inside jitted
    programs.
    """

    mesh_coll_group: Optional[str] = None

    def _rtpu_setup_mesh(self, rank: int, world: int,
                         coll_group: Optional[str] = None) -> None:
        self.mesh_rank = rank
        self.mesh_world = world
        self.mesh_coll_group = coll_group
        if coll_group is not None:
            self._rtpu_init_collective(world, rank, coll_group)

    def _mesh_group_name(self) -> str:
        if self.mesh_coll_group is None:
            raise RuntimeError(
                "this MeshGroup was created without collective_group=; "
                "host-level mesh_* collectives are not wired")
        return self.mesh_coll_group

    def mesh_allreduce(self, tensor, op: str = "sum"):
        from . import collective as col
        return col.allreduce(tensor, group_name=self._mesh_group_name(),
                             op=op)

    def mesh_broadcast(self, tensor, src_rank: int = 0):
        from . import collective as col
        return col.broadcast(tensor, src_rank=src_rank,
                             group_name=self._mesh_group_name())

    def mesh_reducescatter(self, tensor, op: str = "sum"):
        """Reduce across hosts, keep this host's 1/world slice (the
        DCN half of a cross-slice gradient shard: each host feeds its
        slice of the reduced update to its own chips)."""
        from . import collective as col
        return col.reducescatter(tensor, op=op,
                                 group_name=self._mesh_group_name())

    def mesh_allgather(self, tensor):
        """Gather every host's array (rank order) over the host plane."""
        from . import collective as col
        return col.allgather(tensor, group_name=self._mesh_group_name())

    def mesh_barrier(self) -> None:
        from . import collective as col
        col.barrier(group_name=self._mesh_group_name())

    def build_local_mesh(self, spec=None):
        from ..parallel.mesh import build_mesh
        return build_mesh(spec)


class MeshGroup:
    """A gang of host actors driven in lockstep SPMD calls."""

    def __init__(self, actors: List[Any],
                 pg: Optional[PlacementGroup] = None,
                 collective_group: Optional[str] = None):
        self._actors = actors
        self._pg = pg
        self.collective_group = collective_group
        # all ranks are driven concurrently: rank 0's init creates the
        # group coordinator and later ranks block on its appearance
        refs = [a._rtpu_setup_mesh.remote(i, len(actors), collective_group)
                for i, a in enumerate(actors)]
        get(refs)

    @property
    def world_size(self) -> int:
        return len(self._actors)

    @property
    def actors(self) -> List[Any]:
        return list(self._actors)

    def run(self, method_name: str, *args, **kwargs) -> List[Any]:
        """Invoke `method_name` on every host actor concurrently; block
        for all results (lockstep — all hosts must enter the same
        computation, like every multi-controller JAX program)."""
        refs = [getattr(a, method_name).remote(*args, **kwargs)
                for a in self._actors]
        return get(refs)

    def run_async(self, method_name: str, *args, **kwargs) -> List[Any]:
        return [getattr(a, method_name).remote(*args, **kwargs)
                for a in self._actors]

    def run_rank(self, rank: int, method_name: str, *args, **kwargs) -> Any:
        return get(getattr(self._actors[rank], method_name)
                   .remote(*args, **kwargs))

    def shutdown(self) -> None:
        from .. import kill
        if self.collective_group is not None:
            # any member can tear the group down (destroy fences the
            # epoch, sweeps stranded chunks and kills the coordinator);
            # bounded so a dead rank 0 can't hang the gang's teardown
            try:
                get(self._actors[0]._rtpu_destroy_collective.remote(
                    self.collective_group), timeout=15.0)
            except Exception:
                pass
        for a in self._actors:
            try:
                kill(a)
            except Exception:
                pass
        if self._pg is not None:
            remove_placement_group(self._pg)


def mesh_group(actor_cls, num_hosts: int,
               resources_per_host: Optional[dict] = None,
               strategy: str = "STRICT_SPREAD",
               actor_args: Sequence[Any] = (),
               actor_kwargs: Optional[dict] = None,
               collective_group: Optional[str] = None) -> MeshGroup:
    """Gang-schedule `num_hosts` host actors, one per placement bundle.

    `actor_cls` must be a `@ray_tpu.remote` class whose implementation
    inherits `SPMDWorkerBase`. STRICT_SPREAD puts one host actor per
    node — the TPU-pod shape (one worker per TPU-VM host).
    ``collective_group`` additionally joins the hosts into a named
    host-level collective group (ring/tree schedules over the node
    plane) usable via the ``mesh_*`` helpers.
    """
    bundle = dict(resources_per_host or {"CPU": 1})
    pg = placement_group([bundle] * num_hosts, strategy=strategy)
    pg.ready(timeout=60.0)
    actor_kwargs = actor_kwargs or {}
    actors = []
    try:
        for i in range(num_hosts):
            strategy_obj = PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=i)
            opts = {"scheduling_strategy": strategy_obj}
            if "CPU" in bundle:
                opts["num_cpus"] = bundle["CPU"]
            extra = {k: v for k, v in bundle.items() if k not in ("CPU",)}
            if extra:
                opts["resources"] = extra
            actors.append(actor_cls.options(**opts).remote(*actor_args,
                                                           **actor_kwargs))
        return MeshGroup(actors, pg=pg, collective_group=collective_group)
    except Exception:
        # don't leak the gang reservation (or stragglers) on failure
        from .. import kill
        for a in actors:
            try:
                kill(a)
            except Exception:
                pass
        remove_placement_group(pg)
        raise
