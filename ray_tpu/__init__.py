"""ray_tpu — a TPU-native distributed compute framework.

Tasks, actors and a shared-memory object store (the core API of the
reference, ``python/ray/_private/worker.py`` — init/get/put/wait/remote),
plus JAX/XLA-idiomatic ML layers: device-mesh collectives over ICI
(``ray_tpu.comm``), sharded models (``ray_tpu.models``), parallelism rules
(``ray_tpu.parallel``), trainers/tuners/data/serving (``ray_tpu.train`` …).

Heavy JAX modules are imported lazily — the core runtime has no JAX
dependency so worker processes stay lightweight.
"""

from __future__ import annotations

import atexit
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from . import exceptions  # noqa: F401
from ._private import context as _ctx
from ._private import protocol as _P
from ._private.client import CoreClient
from ._private.config import CONFIG
from ._private.gcs import GlobalControlPlane, JobRecord
from ._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID  # noqa: F401
from ._private.node import NodeService
from ._private.object_ref import ObjectRef, ObjectRefGenerator
from .api import ActorClass, ActorHandle, RemoteFunction, method, remote  # noqa: F401
from .runtime_context import get_runtime_context  # noqa: F401

__version__ = "0.1.0"

_global_node: Optional[NodeService] = None
_global_gcs: Optional[GlobalControlPlane] = None
_session_dir: Optional[str] = None
_owns_cluster = False


def init(address: Optional[Any] = None,
         num_cpus: Optional[int] = None,
         num_tpus: Optional[int] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: str = "default",
         object_store_memory: Optional[int] = None,
         ignore_reinit_error: bool = False,
         runtime_env: Optional[Dict[str, Any]] = None,
         _system_config: Optional[Dict[str, Any]] = None) -> None:
    """Start a local node (head) and connect, or connect to an existing
    in-process cluster (pass a ``cluster_utils.Cluster``).

    Reference analogue: ``ray.init`` (``_private/worker.py:1139``) — the
    local-bootstrap path spawns the control plane + node service + worker
    pool; here they live in this process with workers as subprocesses.
    """
    global _global_node, _global_gcs, _session_dir, _owns_cluster
    if _ctx.current_client is not None:
        if ignore_reinit_error:
            return
        raise RuntimeError("ray_tpu.init() called twice; "
                           "call ray_tpu.shutdown() first")
    if _system_config:
        CONFIG.reload(_system_config)

    job_id = JobID.from_random()
    head_tcp_address = None
    if address is not None:
        from . import cluster_utils
        if isinstance(address, cluster_utils.Cluster):
            if address.process_isolated:
                address = address.gcs_address
            else:
                # attach to an in-process multi-node cluster (tests/tools)
                cluster = address
                _global_gcs = cluster.gcs
                _global_node = cluster.head
                _session_dir = cluster.session_dir
                _owns_cluster = False
                address = None
        if isinstance(address, str):
            # attach to a networked cluster: "host:port" of the head GCS
            # (reference analogue: ``ray.init(address=...)`` joining a
            # running cluster). The driver must share a host with the
            # head node — object payloads ride /dev/shm.
            from ._private.gcs_service import RemoteControlPlane
            import json as _json
            _global_gcs = RemoteControlPlane(address)
            try:
                head = _global_gcs.kv_get(b"__rtpu_head_node")
                if head is None:
                    raise ConnectionError(
                        f"no head node registered at {address}")
            except BaseException:
                # don't leak the channel/reader thread or a stale global
                _global_gcs.close()
                _global_gcs = None
                raise
            head = _json.loads(head)
            head_tcp_address = head["address"]
            _global_node = None
            _session_dir = None
            _owns_cluster = False
        elif address is not None and not isinstance(
                address, cluster_utils.Cluster):
            raise ValueError(f"unsupported address: {address!r}")
    else:
        _session_dir = tempfile.mkdtemp(prefix="rtpu_session_")
        _global_gcs = GlobalControlPlane()
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus if num_cpus is not None
                                    else os.cpu_count() or 4))
        if num_tpus is not None:
            res.setdefault("TPU", float(num_tpus))
        elif "TPU" not in res:
            detected = _detect_tpus()
            if detected:
                res["TPU"] = float(detected)
        if object_store_memory:
            CONFIG._values["object_store_memory_mb"] = (
                object_store_memory // (1 << 20))
        _global_node = NodeService(_global_gcs, _session_dir, res)
        _global_node.start()
        _owns_cluster = True

    if _global_node is not None:
        conn = _P.connect_unix(_global_node.socket_path)
        node_id = _global_node.node_id
    else:
        from ._private.ids import NodeID as _NodeID
        conn = _P.connect_address(head_tcp_address)
        node_id = _NodeID.from_hex(head["node_id"])
    client = CoreClient(conn, job_id, WorkerID.from_random(), _P.KIND_DRIVER)
    if _global_node is not None:
        # head driver: large puts go straight to the in-process store
        # (alloc/write/seal, no control-plane round trips)
        client.local_node = _global_node
    if _global_node is None:
        # Ray-Client-equivalent attach: when this driver does not share
        # /dev/shm with the head node, object payloads must ride the
        # socket instead of shared memory. Primary signal: read the
        # head's shm probe token back (a direct capability test —
        # hostname equality lies when containers share names). The
        # RTPU_NODE_HOST override keeps the test hook for simulating
        # foreign hosts on one machine.
        my_host = os.environ.get("RTPU_NODE_HOST")
        head_host = head.get("host")
        if my_host:
            client.wire_data_plane = bool(head_host) and head_host != my_host
        else:
            probe = head.get("shm_probe") or (None, None)
            same_shm = False
            if probe[0]:
                try:
                    with open(probe[0]) as _f:
                        same_shm = _f.read().strip() == probe[1]
                except OSError:
                    same_shm = False
            else:
                import socket as _socket
                same_shm = (not head_host
                            or head_host == _socket.gethostname())
            client.wire_data_plane = not same_shm
    conn.send((_P.REGISTER, (_P.KIND_DRIVER, client.worker_id.binary(),
                             os.getpid())))
    client.start_reader()
    client.namespace = namespace
    client.node_id = node_id
    from ._private import runtime_env as _renv
    client.job_runtime_env = _renv.validate(runtime_env)
    _ctx.current_client = client
    _global_gcs.register_job(JobRecord(job_id=job_id, driver_pid=os.getpid(),
                                       start_time=time.time()))
    _install_driver_failure_hook()
    atexit.register(shutdown)


_prev_excepthook = None


def _install_driver_failure_hook() -> None:
    """Driver shutdown on an uncaught error is a terminal failure: hook
    ``sys.excepthook`` (once per process, chained) so the dying driver
    auto-captures a post-mortem debug bundle while its client is still
    connected — the corpse `rtpu autopsy` reads after the session is
    gone. Gated by ``debug_bundle_on_failure``."""
    global _prev_excepthook
    import sys as _sys
    if _prev_excepthook is not None:
        return
    _prev_excepthook = _sys.excepthook

    def _hook(tp, val, tb):
        try:
            if (_ctx.current_client is not None
                    and not issubclass(tp, KeyboardInterrupt)):
                from ._private import debug_bundle
                debug_bundle.auto_capture(
                    "driver_error",
                    fields={"error": f"{tp.__name__}: {val}"})
        except Exception:   # noqa: BLE001 — never mask the real error
            pass
        _prev_excepthook(tp, val, tb)

    _sys.excepthook = _hook


def _detect_tpus() -> int:
    """TPU autodetection as a first-class resource (north-star requirement;
    reference analogue: ``_private/accelerator.py:38-45``)."""
    chips = os.environ.get("TPU_CHIPS")
    if chips:
        return int(chips)
    # visible TPU chips via /dev (TPU VMs expose accel devices)
    count = 0
    for i in range(8):
        if os.path.exists(f"/dev/accel{i}") or os.path.exists(f"/dev/vfio/{i}"):
            count += 1
    return count


def is_initialized() -> bool:
    return _ctx.current_client is not None


def shutdown() -> None:
    global _global_node, _global_gcs, _session_dir, _owns_cluster
    client = _ctx.current_client
    if client is None:
        return
    if CONFIG.tracing_enabled:
        from .util import tracing as _tracing
        _tracing.flush()          # ship driver-side spans before detach
    _ctx.current_client = None
    try:
        client.close()
    except Exception:
        pass
    if _owns_cluster and _global_node is not None:
        _global_node.stop()
        if _session_dir:
            import shutil
            shutil.rmtree(_session_dir, ignore_errors=True)
    if _global_gcs is not None and hasattr(_global_gcs, "close"):
        try:
            _global_gcs.close()   # remote attach: drop the GCS channel
        except Exception:
            pass
    _global_node = None
    _global_gcs = None
    _session_dir = None
    _owns_cluster = False
    # telemetry is session-scoped too: the next init() gets a fresh
    # control plane, so local shard totals must not leak deltas into it
    from ._private import telemetry as _telemetry
    _telemetry.reset()
    # so are the collective flight-recorder's ring and watermark tables
    # (a stale ring would bleed this session's collective spans into the
    # next session's state.timeline())
    from ._private import flight_recorder as _flight_recorder
    _flight_recorder.reset()
    # and tracing's local span buffer: the rate-limited maybe_flush can
    # leave the session's last request spans buffered here — shipping
    # them after the next init() would graft a dead session's request
    # lane onto the new plane's timeline
    from .util import tracing as _tracing
    _tracing.drain()
    # _system_config is session-scoped: the next init() must not inherit
    # this session's overrides (they'd silently change its behavior)
    CONFIG.reload()
    atexit.unregister(shutdown)


def put(value: Any) -> ObjectRef:
    """Store a value in the object store (reference: ``worker.py:2590``)."""
    return _ctx.require_client().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    """Fetch object values, blocking (reference: ``worker.py:2475``)."""
    client = _ctx.require_client()
    if isinstance(refs, ObjectRef):
        return client.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects ObjectRef or list, got {type(refs)}")
    if not refs:
        return []
    return client.get(list(refs), timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    """Wait for ``num_returns`` of ``refs`` to complete (reference:
    ``worker.py:2653``)."""
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return _ctx.require_client().wait(list(refs), num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    """Forcibly terminate an actor (reference: ``ray.kill``)."""
    _ctx.require_client().kill_actor(actor.actor_id, no_restart)


def exit_actor() -> None:
    """Intentionally terminate the CURRENT actor from inside one of its
    methods (reference: ``ray.actor.exit_actor``): the node kills this
    actor's worker with restarts suppressed, and the executing method
    unwinds — its caller observes the actor's death rather than a
    return value."""
    aid = _ctx.current_actor_id
    if aid is None:
        raise RuntimeError(
            "exit_actor() can only be called inside an actor method")
    client = _ctx.require_client()
    try:
        client.actor_exit(aid, "exit_actor()")
    except OSError:
        # the node never hears the intent, so restart suppression is
        # lost — the conn-death path will treat this as a crash (and
        # may restart the actor); say so instead of silently diverging
        import sys as _sys
        print(f"[ray_tpu] exit_actor(): ACTOR_EXIT send failed for "
              f"{aid.hex()[:12]} (connection down); the actor may be "
              "restarted as a crash", file=_sys.stderr)
    raise SystemExit(0)


def actor_checkpoint() -> int:
    """Snapshot the CURRENT actor's state now, from inside one of its
    methods: calls the actor's opt-in ``save_checkpoint()`` and
    persists the result in the control plane (synchronously — when this
    returns, a restart restores at least this state). A restarted actor
    whose class defines ``restore_checkpoint(state)`` replays its
    latest snapshot before any queued call drains. Returns the
    checkpoint's sequence number. See also the periodic trigger,
    ``actor_checkpoint_interval_calls``."""
    hook = _ctx.actor_checkpoint_hook
    if hook is None or _ctx.current_actor_id is None:
        raise RuntimeError(
            "actor_checkpoint() can only be called inside a method of "
            "an actor that defines save_checkpoint()")
    return hook()


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Cancel the task that produces ``ref`` (reference: ``ray.cancel``)."""
    _ctx.require_client().cancel_task(ref.task_id(), force)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    """Look up a named actor (reference: ``worker.py:2784``). Defaults to
    the namespace passed to ``init()``."""
    client = _ctx.require_client()
    namespace = namespace or _ctx.active_namespace()
    info = client.get_named_actor(name, namespace)
    if info is None:
        raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
    return ActorHandle(info["actor_id"], info["name"])


def free(refs: Sequence[ObjectRef]) -> None:
    if isinstance(refs, ObjectRef):
        refs = [refs]
    _ctx.require_client().free(list(refs))


def nodes() -> List[dict]:
    return _ctx.require_client().cluster_info("nodes")


def cluster_resources() -> Dict[str, float]:
    return _ctx.require_client().cluster_info("resources_total")


def available_resources() -> Dict[str, float]:
    return _ctx.require_client().cluster_info("resources_available")
