"""Decoder-only transformer, TPU-first.

One implementation covers the GPT-2 family (learned positions, GELU MLP,
LayerNorm) and the Llama family (RoPE, SwiGLU, RMSNorm, GQA) through
`GPTConfig` switches — the reference ships these as external torch models
driven by Ray Train (`release/train_tests`, SURVEY §6 north-star configs);
here the model itself is framework-native.

TPU-first choices:
  * scan-over-layers with stacked params — one compiled block body,
    compile time O(1) in depth, and GSPMD gathers FSDP-sharded weights
    one layer at a time (ZeRO-3 semantics for free).
  * logical-axis names on every param/activation dim; the mesh mapping
    lives in `ray_tpu.parallel.sharding.ShardingRules`.
  * attention dispatch: Pallas flash kernel on one sequence shard,
    ring attention (`ops/ring_attention.py`) over the `sp` mesh axis when
    the sequence is context-parallel — both wrapped in `shard_map` so the
    kernel sees local blocks; everything else is GSPMD.
  * bf16 activations, f32 params/optimizer (cast at use).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..ops.attention import attention_reference, dot_product_attention
from ..ops.ring_attention import ring_attention
from ..parallel.sharding import (DEFAULT_RULES, ShardingRules,
                                 with_logical_constraint)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # GPT-2 vocab padded to a multiple of 128
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    n_kv_heads: Optional[int] = None  # None -> n_heads (MHA); < n_heads -> GQA
    d_ff: Optional[int] = None        # None -> 4*d_model (gelu) / 8/3*d (swiglu)
    max_seq_len: int = 1024
    # family switches
    activation: str = "gelu"          # "gelu" | "swiglu"
    norm: str = "layernorm"           # "layernorm" | "rmsnorm"
    positions: str = "learned"        # "learned" | "rope"
    rope_theta: float = 10000.0
    tie_embeddings: bool = True
    # pipeline parallelism: microbatches per global batch (0 -> = pp).
    # Stages come from the mesh's pp axis; GSPMD-style schedule (scan
    # over steps, stage-sharded rolling buffer -> collective-permute).
    pp_microbatches: int = 0
    # mixture-of-experts (0 = dense; EP is absent from the reference,
    # SURVEY §2.4 — first-class here)
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coeff: float = 0.01
    # numerics
    dtype: Any = jnp.bfloat16         # activation dtype
    param_dtype: Any = jnp.float32
    # training
    remat: bool = True
    # what the per-block checkpoint saves for backward: "full" recomputes
    # everything (lowest memory, ~4/3x flops); "dots" saves matmul
    # outputs and recomputes only cheap elementwise ops (the usual MFU
    # sweet spot when HBM allows)
    remat_policy: str = "full"
    z_loss: float = 1e-4
    # attention kernel: "auto" | "pallas" | "pallas_interpret" | "reference"
    attention_impl: str = "auto"
    attn_block_q: int = 512
    attn_block_k: int = 512

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.activation == "swiglu":
            # llama convention: 8/3 * d, rounded up to a multiple of 256
            raw = int(8 * self.d_model / 3)
            return (raw + 255) // 256 * 256
        return 4 * self.d_model

    @property
    def n_params(self) -> int:
        """Approximate parameter count (excludes norms/bias)."""
        d, f, v = self.d_model, self.ff_dim, self.vocab_size
        hd, h, hk = self.head_dim, self.n_heads, self.kv_heads
        attn = d * h * hd + 2 * d * hk * hd + h * hd * d
        if self.n_experts > 0:
            mlp = self.n_experts * 3 * d * f + d * self.n_experts
        else:
            mlp = (3 if self.activation == "swiglu" else 2) * d * f
        emb = v * d * (1 if self.tie_embeddings else 2)
        return self.n_layers * (attn + mlp) + emb


# --- presets ---------------------------------------------------------------

def gpt2_small(**kw) -> GPTConfig:
    return GPTConfig(n_layers=12, d_model=768, n_heads=12, **kw)


def gpt2_medium(**kw) -> GPTConfig:
    return GPTConfig(n_layers=24, d_model=1024, n_heads=16, **kw)


def gpt2_large(**kw) -> GPTConfig:
    return GPTConfig(n_layers=36, d_model=1280, n_heads=20, **kw)


def _llama(**kw) -> GPTConfig:
    base = dict(activation="swiglu", norm="rmsnorm", positions="rope",
                tie_embeddings=False, vocab_size=32000, max_seq_len=2048)
    base.update(kw)
    return GPTConfig(**base)


def llama_tiny(**kw) -> GPTConfig:
    """Test-scale llama-style config (CPU-friendly)."""
    return _llama(n_layers=2, d_model=128, n_heads=4, n_kv_heads=2,
                  vocab_size=512, max_seq_len=256, **kw)


def llama_1b(**kw) -> GPTConfig:
    return _llama(n_layers=16, d_model=2048, n_heads=16, n_kv_heads=8, **kw)


def llama_7b(**kw) -> GPTConfig:
    return _llama(n_layers=32, d_model=4096, n_heads=32, d_ff=11008,
                  max_seq_len=4096, **kw)


# --- init ------------------------------------------------------------------

def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class GPT:
    """Functional model: `init` → params pytree, `apply` → logits.

    Parallelism is injected at construction: a `Mesh` + `ShardingRules`.
    With no mesh (unit tests, single device) everything degrades to plain
    single-device JAX.
    """

    def __init__(self, config: GPTConfig, mesh: Optional[Mesh] = None,
                 rules: Optional[ShardingRules] = None):
        self.config = config
        self.mesh = mesh
        self.rules = rules if rules is not None else DEFAULT_RULES
        if self.pp_stages > 1:
            if config.n_layers % self.pp_stages:
                raise ValueError(
                    f"n_layers={config.n_layers} must divide into "
                    f"pp={self.pp_stages} stages")
            if config.n_experts > 0:
                raise NotImplementedError(
                    "EP+PP combined (MoE aux-loss masking across pipeline "
                    "bubbles) is not supported yet")

    @property
    def pp_stages(self) -> int:
        if self.mesh is None:
            return 1
        ax = self.rules.mesh_axes("stage")
        if isinstance(ax, str) and ax in self.mesh.shape:
            return self.mesh.shape[ax]
        return 1

    # -- parameters --------------------------------------------------------

    def init(self, rng: jax.Array) -> Params:
        c = self.config
        pd = c.param_dtype
        d, f, hd = c.d_model, c.ff_dim, c.head_dim
        h, hk, L = c.n_heads, c.kv_heads, c.n_layers
        std = 0.02
        resid_std = std / math.sqrt(2 * L)
        keys = jax.random.split(rng, 12)

        def ones(shape):
            return jnp.ones(shape, pd)

        blocks = {
            "norm1": ones((L, d)),
            "norm2": ones((L, d)),
            "wq": _normal(keys[0], (L, d, h, hd), std, pd),
            "wk": _normal(keys[1], (L, d, hk, hd), std, pd),
            "wv": _normal(keys[2], (L, d, hk, hd), std, pd),
            "wo": _normal(keys[3], (L, h, hd, d), resid_std, pd),
        }
        if c.n_experts > 0:
            E = c.n_experts
            blocks["router"] = _normal(keys[4], (L, d, E), std, pd)
            blocks["w_up"] = _normal(keys[5], (L, E, d, f), std, pd)
            blocks["w_gate"] = _normal(keys[6], (L, E, d, f), std, pd)
            blocks["w_down"] = _normal(keys[10], (L, E, f, d), resid_std,
                                       pd)
        else:
            blocks["w_up"] = _normal(keys[4], (L, d, f), std, pd)
            blocks["w_down"] = _normal(keys[5], (L, f, d), resid_std, pd)
            if c.activation == "swiglu":
                blocks["w_gate"] = _normal(keys[6], (L, d, f), std, pd)
        if c.norm == "layernorm":
            blocks["bias1"] = jnp.zeros((L, d), pd)
            blocks["bias2"] = jnp.zeros((L, d), pd)
        params: Params = {
            "tok_embed": _normal(keys[7], (c.vocab_size, d), std, pd),
            "blocks": blocks,
            "norm_f": ones((d,)),
        }
        if c.positions == "learned":
            params["pos_embed"] = _normal(keys[8], (c.max_seq_len, d), std,
                                          pd)
        if c.norm == "layernorm":
            params["bias_f"] = jnp.zeros((d,), pd)
        if not c.tie_embeddings:
            params["lm_head"] = _normal(keys[9], (d, c.vocab_size), std, pd)
        P = self.pp_stages
        if P > 1:
            # stage-stack: [L, ...] -> [P, L/P, ...]; the stage axis is
            # sharded over pp so each stage holds only its layers
            params["blocks"] = jax.tree_util.tree_map(
                lambda a: a.reshape((P, L // P) + a.shape[1:]),
                params["blocks"])
        return params

    def param_logical_axes(self) -> Params:
        """Pytree matching `init` output: tuples of logical axis names."""
        c = self.config
        blocks = {
            "norm1": ("layers", None),
            "norm2": ("layers", None),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
        }
        if c.n_experts > 0:
            blocks["router"] = ("layers", "embed", None)
            blocks["w_up"] = ("layers", "expert", "embed", "mlp")
            blocks["w_gate"] = ("layers", "expert", "embed", "mlp")
            blocks["w_down"] = ("layers", "expert", "mlp", "embed")
        else:
            blocks["w_up"] = ("layers", "embed", "mlp")
            blocks["w_down"] = ("layers", "mlp", "embed")
            if c.activation == "swiglu":
                blocks["w_gate"] = ("layers", "embed", "mlp")
        if c.norm == "layernorm":
            blocks["bias1"] = ("layers", None)
            blocks["bias2"] = ("layers", None)
        if self.pp_stages > 1:
            blocks = {k: ("stage",) + v for k, v in blocks.items()}
        axes: Params = {
            "tok_embed": ("vocab", "embed"),
            "blocks": blocks,
            "norm_f": (None,),
        }
        if c.positions == "learned":
            axes["pos_embed"] = (None, "embed")
        if c.norm == "layernorm":
            axes["bias_f"] = (None,)
        if not c.tie_embeddings:
            axes["lm_head"] = ("embed", "vocab")
        return axes

    # -- building blocks ---------------------------------------------------

    def _norm(self, x, scale, bias):
        c = self.config
        xf = x.astype(jnp.float32)
        if c.norm == "rmsnorm":
            xf = xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6)
            return (xf * scale.astype(jnp.float32)).astype(c.dtype)
        mean = jnp.mean(xf, -1, keepdims=True)
        var = jnp.var(xf, -1, keepdims=True)
        xf = (xf - mean) * lax.rsqrt(var + 1e-5)
        out = xf * scale.astype(jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
        return out.astype(c.dtype)

    def _rope(self, x, positions):
        """x: [B, S, H, D_h]; positions: [B, S]."""
        c = self.config
        hd = x.shape[-1]
        half = hd // 2
        freqs = c.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32)
                                 / half)
        angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
        return out.astype(x.dtype)

    def _sp_size(self) -> int:
        """Size of the mesh axis act_seq maps to (sequence parallelism)."""
        if self.mesh is None:
            return 1
        ax = self.rules.mesh_axes("act_seq")
        if isinstance(ax, str) and ax in self.mesh.shape:
            return self.mesh.shape[ax]
        return 1

    def _attention(self, q, k, v):
        """q: [B, S, H, Dh], k/v: [B, S, Hk, Dh] → [B, S, H, Dh].

        Kernels want [B, H, S, Dh]; ring attention additionally wants the
        sequence axis *locally* sharded, so both pallas paths run under
        shard_map with specs derived from the mesh.
        """
        c = self.config
        qt = jnp.transpose(q, (0, 2, 1, 3))
        kt = jnp.transpose(k, (0, 2, 1, 3))
        vt = jnp.transpose(v, (0, 2, 1, 3))
        sp = self._sp_size()
        if getattr(self, "_in_pipeline", False):
            # pipeline mode runs blocks under vmap over the stage axis;
            # shard_map can't nest there, so use the einsum attention and
            # let GSPMD partition it (pallas-in-pipeline: future work)
            ot = attention_reference(qt, kt, vt, causal=True)
        elif sp > 1:
            # Specs derive from the rules table like every other sharding
            # decision; the ring axis is whatever act_seq maps to.
            spec_q = self.rules.spec("act_batch", "act_heads", "act_seq",
                                     "head_dim")
            spec_kv = self.rules.spec("act_batch", "act_kv_heads",
                                      "act_seq", "head_dim")
            seq_axis = self.rules.mesh_axes("act_seq")
            assert isinstance(seq_axis, str), (
                "ring attention needs act_seq mapped to a single mesh axis")

            def local(qb, kb, vb):
                return ring_attention(qb, kb, vb, seq_axis, True, None,
                                      c.attention_impl, c.attn_block_q,
                                      c.attn_block_k)

            ot = jax.shard_map(local, mesh=self.mesh,
                               in_specs=(spec_q, spec_kv, spec_kv),
                               out_specs=spec_q, check_vma=False)(qt, kt, vt)
        elif self.mesh is not None:
            spec_q = self.rules.spec("act_batch", "act_heads", None, None)
            spec_kv = self.rules.spec("act_batch", "act_kv_heads", None,
                                      None)

            def local(qb, kb, vb):
                return dot_product_attention(
                    qb, kb, vb, causal=True, impl=c.attention_impl,
                    block_q=c.attn_block_q, block_k=c.attn_block_k)

            ot = jax.shard_map(local, mesh=self.mesh,
                               in_specs=(spec_q, spec_kv, spec_kv),
                               out_specs=spec_q, check_vma=False)(qt, kt, vt)
        else:
            ot = dot_product_attention(qt, kt, vt, causal=True,
                                       impl=c.attention_impl,
                                       block_q=c.attn_block_q,
                                       block_k=c.attn_block_k)
        return jnp.transpose(ot, (0, 2, 1, 3))

    def _constrain(self, x, *logical):
        return with_logical_constraint(x, *logical, rules=self.rules,
                                       mesh=self.mesh)

    def _block(self, x, positions, w):
        """One transformer block. x: [B, S, D] bf16."""
        c = self.config
        dt = c.dtype

        h = self._norm(x, w["norm1"], w.get("bias1"))
        q = jnp.einsum("bsd,dhk->bshk", h, w["wq"].astype(dt))
        k = jnp.einsum("bsd,dhk->bshk", h, w["wk"].astype(dt))
        v = jnp.einsum("bsd,dhk->bshk", h, w["wv"].astype(dt))
        if c.positions == "rope":
            q = self._rope(q, positions)
            k = self._rope(k, positions)
        q = self._constrain(q, "act_batch", "act_seq", "act_heads",
                            "head_dim")
        k = self._constrain(k, "act_batch", "act_seq", "act_kv_heads",
                            "head_dim")
        attn = self._attention(q, k, v)
        attn = jnp.einsum("bshk,hkd->bsd", attn, w["wo"].astype(dt))
        x = x + self._constrain(attn, "act_batch", "act_seq", "act_embed")

        h = self._norm(x, w["norm2"], w.get("bias2"))
        aux = jnp.zeros((), jnp.float32)
        if c.n_experts > 0:
            from .moe import moe_ffn
            down, moe_metrics = moe_ffn(
                h, w["router"], w["w_up"], w["w_gate"], w["w_down"],
                top_k=c.moe_top_k,
                capacity_factor=c.moe_capacity_factor, dtype=dt)
            aux = moe_metrics["moe_aux_loss"]
        else:
            up = jnp.einsum("bsd,df->bsf", h, w["w_up"].astype(dt))
            if c.activation == "swiglu":
                gate = jnp.einsum("bsd,df->bsf", h,
                                  w["w_gate"].astype(dt))
                act = jax.nn.silu(gate) * up
            else:
                act = jax.nn.gelu(up, approximate=True)
            act = self._constrain(act, "act_batch", "act_seq", "act_mlp")
            down = jnp.einsum("bsf,fd->bsd", act, w["w_down"].astype(dt))
        x = x + self._constrain(down, "act_batch", "act_seq", "act_embed")
        return x, aux

    # -- forward -----------------------------------------------------------

    def apply(self, params: Params, tokens: jax.Array,
              positions: Optional[jax.Array] = None) -> jax.Array:
        """tokens: [B, S] int32 → logits [B, S, V] (f32)."""
        return self.forward_with_aux(params, tokens, positions)[0]

    def forward_with_aux(self, params: Params, tokens: jax.Array,
                         positions: Optional[jax.Array] = None):
        """Returns (logits, aux_losses dict) — MoE load-balance terms."""
        c = self.config
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32),
                tokens.shape)
        # Embedding lookup with an EXPLICIT all-gather of the
        # (vocab/tp, embed/fsdp)-sharded table and (batch, seq)-sharded
        # indices: left to inference, the partitioner shards the gather
        # output on tp and then falls back to "involuntary full
        # rematerialization" resharding it to (batch, seq) — the
        # spmd_partitioner.cc warning in MULTICHIP_r03. Replicated
        # operand + sharded indices computes the gather directly in the
        # activation sharding.
        tbl = self._constrain(params["tok_embed"].astype(c.dtype),
                              None, None)
        tokens = self._constrain(tokens, "act_batch", "act_seq")
        x = tbl[tokens]
        if c.positions == "learned":
            pos_tbl = self._constrain(params["pos_embed"].astype(c.dtype),
                                      None, None)
            x = x + pos_tbl[positions]
        x = self._constrain(x, "act_batch", "act_seq", "act_embed")

        block_fn = self._block
        if c.remat:
            policies = {
                "full": jax.checkpoint_policies.nothing_saveable,
                "dots":
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            }
            if c.remat_policy not in policies:
                raise ValueError(
                    f"remat_policy must be one of {sorted(policies)}, "
                    f"got {c.remat_policy!r} (use remat=False to disable "
                    "rematerialization entirely)")
            block_fn = jax.checkpoint(block_fn,
                                      policy=policies[c.remat_policy])

        if self.pp_stages > 1:
            x = self._pipeline_blocks(block_fn, params["blocks"], x,
                                      positions)
            aux_per_layer = jnp.zeros((1,), jnp.float32)
        else:
            def scan_body(x, layer_w):
                x, aux = block_fn(x, positions, layer_w)
                return x, aux

            x, aux_per_layer = lax.scan(scan_body, x, params["blocks"])
        x = self._norm(x, params["norm_f"], params.get("bias_f"))
        if c.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", x,
                                params["tok_embed"].astype(c.dtype))
        else:
            logits = jnp.einsum("bsd,dv->bsv", x,
                                params["lm_head"].astype(c.dtype))
        logits = self._constrain(logits, "act_batch", "act_seq", "act_vocab")
        return logits.astype(jnp.float32), {
            "moe_aux_loss": aux_per_layer.mean()}

    def _pipeline_blocks(self, block_fn, blocks: Params, x: jax.Array,
                         positions: jax.Array) -> jax.Array:
        """GPipe schedule, GSPMD formulation (reference has no native PP,
        SURVEY §2.4 — Alpa-on-Ray only). Stage-stacked params [P, L/P, …]
        shard over pp; a [P, b, S, D] rolling buffer carries each
        microbatch through the stages; `jnp.roll` on the stage-sharded
        axis lowers to collective-permute over ICI. M microbatches take
        M + P - 1 steps (the usual bubble)."""
        c = self.config
        P = self.pp_stages
        B, S, D = x.shape
        M = c.pp_microbatches or P
        if B % M:
            raise ValueError(f"batch {B} must divide into {M} microbatches")
        mb = B // M
        x_mb = x.reshape(M, mb, S, D)
        x_mb = self._constrain(x_mb, None, "act_batch", "act_seq",
                               "act_embed")
        pos_mb = positions.reshape(M, mb, S)[0]

        self._in_pipeline = True
        try:
            def stage_step(carry, t):
                state, outs = carry
                # shift: stage s hands its activation to stage s+1
                state = jnp.roll(state, shift=1, axis=0)
                # feed the next microbatch into stage 0
                inp = lax.dynamic_index_in_dim(
                    x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
                state = state.at[0].set(
                    jnp.where(t < M, inp, state[0]))
                state = self._constrain(state, "stage", "act_batch",
                                        "act_seq", "act_embed")

                # every stage applies its L/P layers (vmap over stages;
                # per-stage scan over layers)
                def one_stage(stage_params, xs):
                    def body(h, layer_w):
                        h, _ = block_fn(h, pos_mb, layer_w)
                        return h, None
                    out, _ = lax.scan(body, xs, stage_params)
                    return out

                state = jax.vmap(one_stage)(blocks, state)
                state = self._constrain(state, "stage", "act_batch",
                                        "act_seq", "act_embed")
                # collect the last stage's output once the fill drains
                out_idx = jnp.clip(t - (P - 1), 0, M - 1)
                outs = lax.cond(
                    t >= P - 1,
                    lambda o: lax.dynamic_update_index_in_dim(
                        o, state[P - 1], out_idx, axis=0),
                    lambda o: o, outs)
                return (state, outs), None

            state0 = jnp.zeros((P, mb, S, D), c.dtype)
            outs0 = jnp.zeros((M, mb, S, D), c.dtype)
            (_, outs), _ = lax.scan(stage_step, (state0, outs0),
                                    jnp.arange(M + P - 1))
        finally:
            self._in_pipeline = False
        return outs.reshape(B, S, D)

    def loss(self, params: Params, batch: Dict[str, jax.Array]
             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Next-token cross entropy (+ z-loss) with an optional loss mask.

        batch: {"tokens": [B, S] int32, optional "loss_mask": [B, S]}.
        Targets are tokens shifted left; the final position is masked.
        """
        c = self.config
        tokens = batch["tokens"]
        logits, aux = self.forward_with_aux(params, tokens)  # [B,S,V] f32
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1)
        mask = jnp.concatenate(
            [jnp.ones_like(tokens[:, 1:], jnp.float32),
             jnp.zeros_like(tokens[:, :1], jnp.float32)], axis=1)
        if "loss_mask" in batch:
            mask = mask * batch["loss_mask"].astype(jnp.float32)

        lse = jax.nn.logsumexp(logits, axis=-1)            # [B, S]
        true_logit = jnp.take_along_axis(
            logits, targets[..., None], axis=-1)[..., 0]   # [B, S]
        nll = lse - true_logit
        total = jnp.maximum(mask.sum(), 1.0)
        loss = (nll * mask).sum() / total
        if c.z_loss:
            loss = loss + c.z_loss * (lse ** 2 * mask).sum() / total
        metrics = {
            "loss": loss,
            "ppl_log": (nll * mask).sum() / total,
            "tokens": mask.sum(),
        }
        if c.n_experts > 0:
            loss = loss + c.moe_aux_coeff * aux["moe_aux_loss"]
            metrics["moe_aux_loss"] = aux["moe_aux_loss"]
            metrics["loss"] = loss
        return loss, metrics
