"""ray_tpu.models — TPU-native model zoo.

Flagship: a decoder-only transformer (``gpt.py``) covering the GPT-2 and
Llama families through config switches, written as pure-JAX functional
code with logical-axis sharding (``ray_tpu.parallel.sharding``) so every
parallelism strategy (dp/fsdp/tp/sp/pp/ep) is a mesh change, not a model
change. Training step + optimizer live in ``training.py``.
"""

from .gpt import (  # noqa: F401
    GPT,
    GPTConfig,
    gpt2_small,
    gpt2_medium,
    gpt2_large,
    llama_tiny,
    llama_1b,
    llama_7b,
)
from .training import (  # noqa: F401
    TrainState,
    make_optimizer,
    make_train_step,
    init_train_state,
)
