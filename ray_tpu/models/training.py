"""Training step factory: optimizer, TrainState, sharded jit train step.

The reference's training loop is user code orchestrated by Ray Train
(`train/data_parallel_trainer.py:484`, DDP wrap `train_loop_utils.py:74`);
gradient sync is NCCL allreduce hidden inside torch. TPU-native: the whole
step — forward, backward, optimizer — is ONE jitted SPMD program over the
mesh; GSPMD inserts the psums/all-gathers implied by the param/batch
shardings (dp gradient reduction, fsdp ZeRO gathering, tp partials).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import DEFAULT_RULES, ShardingRules
from .gpt import GPT

Params = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Params
    opt_state: Any


def make_optimizer(learning_rate: float = 3e-4,
                   warmup_steps: int = 100,
                   total_steps: int = 10000,
                   weight_decay: float = 0.1,
                   b1: float = 0.9, b2: float = 0.95,
                   grad_clip: float = 1.0,
                   schedule: str = "cosine") -> optax.GradientTransformation:
    if schedule == "cosine":
        lr = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps,
            max(total_steps, warmup_steps + 1), learning_rate * 0.1)
    else:
        lr = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(lr, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def state_logical_axes(model: GPT, optimizer: optax.GradientTransformation,
                       sample_params: Optional[Params] = None) -> Any:
    """Logical-axis pytree for a whole TrainState.

    Optimizer state (adam mu/nu) shards like the params it mirrors —
    subtrees of the optimizer state whose structure equals the param tree
    get the param axes; everything else (counts, schedule scalars) is
    replicated. Structure is discovered via `eval_shape` (no allocation).
    """
    param_axes = model.param_logical_axes()
    if sample_params is None:
        sample_params = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0)))
    param_treedef = jax.tree_util.tree_structure(sample_params)

    def _axes_like(node):
        if jax.tree_util.tree_structure(node) == param_treedef:
            return param_axes
        if isinstance(node, dict):
            return {k: _axes_like(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            children = [_axes_like(c) for c in node]
            if hasattr(node, "_fields"):      # namedtuple (optax states)
                return type(node)(*children)
            return type(node)(children)
        shape = getattr(node, "shape", ())
        return tuple([None] * len(shape))

    opt_shape = jax.eval_shape(optimizer.init, sample_params)
    return TrainState(step=(), params=param_axes,
                      opt_state=_axes_like(opt_shape))


def _is_axes(x):
    return x is None or (isinstance(x, tuple)
                         and all(a is None or isinstance(a, str) for a in x))


def state_shardings(model: GPT, optimizer: optax.GradientTransformation,
                    mesh: Mesh,
                    rules: Optional[ShardingRules] = None) -> Any:
    rules = rules if rules is not None else model.rules
    axes = state_logical_axes(model, optimizer)
    return jax.tree_util.tree_map(
        lambda logical: NamedSharding(mesh, rules.spec(*logical))
        if logical != () else NamedSharding(mesh, P()),
        axes, is_leaf=_is_axes)


def init_train_state(model: GPT, optimizer: optax.GradientTransformation,
                     rng: jax.Array,
                     mesh: Optional[Mesh] = None) -> TrainState:
    """Initialize params + optimizer state, sharded from birth.

    With a mesh, init runs under jit with out_shardings so large models
    never materialize unsharded on one device.
    """

    def _init():
        params = model.init(rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    if mesh is None:
        return _init()
    shardings = state_shardings(model, optimizer, mesh)
    return jax.jit(_init, out_shardings=shardings)()


def batch_shardings(mesh: Mesh,
                    rules: Optional[ShardingRules] = None) -> Any:
    rules = rules if rules is not None else DEFAULT_RULES
    return NamedSharding(mesh, rules.spec("act_batch", "act_seq"))


def make_train_step(model: GPT, optimizer: optax.GradientTransformation,
                    mesh: Optional[Mesh] = None,
                    donate: bool = True
                    ) -> Callable[[TrainState, Dict[str, jax.Array]],
                                  Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted SPMD train step.

    Returns step(state, batch) -> (state, metrics). batch arrays are
    expected sharded over ("act_batch", "act_seq") — use
    `batch_shardings(mesh)` + `jax.device_put`.
    """

    def train_step(state: TrainState, batch: Dict[str, jax.Array]):
        grad_fn = jax.value_and_grad(model.loss, has_aux=True)
        (loss, metrics), grads = grad_fn(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state)
        return new_state, metrics

    if mesh is None:
        return jax.jit(train_step, donate_argnums=(0,) if donate else ())

    shardings = state_shardings(model, optimizer, mesh)
    return jax.jit(
        train_step,
        in_shardings=(shardings, None),
        out_shardings=(shardings, None),
        donate_argnums=(0,) if donate else (),
    )


def eval_step_fn(model: GPT, mesh: Optional[Mesh] = None):
    def eval_step(params, batch):
        _, metrics = model.loss(params, batch)
        return metrics

    if mesh is None:
        return jax.jit(eval_step)
    rules = model.rules
    param_shardings = jax.tree_util.tree_map(
        lambda logical: NamedSharding(mesh, rules.spec(*logical)),
        model.param_logical_axes(), is_leaf=_is_axes)
    return jax.jit(eval_step, in_shardings=(param_shardings, None))


def flops_per_token(config) -> float:
    """~6 * n_params non-embedding FLOPs/token (fwd+bwd), attention extra.

    Used by bench.py to report MFU.
    """
    n = config.n_params - config.vocab_size * config.d_model * (
        1 if config.tie_embeddings else 2)
    attn_extra = 12 * config.n_layers * config.d_model * config.max_seq_len
    # lm head matmul counts (it's a real matmul): 6 * d * V
    head = 6 * config.d_model * config.vocab_size
    return 6.0 * n + attn_extra + head
