"""Mixture-of-Experts FFN with expert parallelism.

The reference has NO native MoE/EP (SURVEY §2.4: "absent — only via
external frameworks"); here it's first-class. Switch/Top-k routing with
capacity-bounded dense dispatch — the XLA-friendly formulation: token →
slot assignment becomes one-hot dispatch/combine einsums (MXU work, no
ragged shapes), expert weights carry a leading "expert" logical axis
sharded over the ``ep`` mesh axis, so the dispatch einsum induces the
all-to-all over ICI.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def moe_ffn(x: jax.Array, router_w: jax.Array, w_up: jax.Array,
            w_gate: jax.Array, w_down: jax.Array, *,
            top_k: int = 2, capacity_factor: float = 1.25,
            dtype=jnp.bfloat16) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """x: [B, S, D]; router_w: [D, E]; w_up/w_gate: [E, D, F];
    w_down: [E, F, D] → ([B, S, D], aux metrics incl. load-balance loss).
    """
    b, s, d = x.shape
    e = router_w.shape[-1]
    n_tokens = b * s
    capacity = max(1, int(capacity_factor * top_k * n_tokens / e))

    xf = x.reshape(n_tokens, d)
    logits = (xf.astype(jnp.float32)
              @ router_w.astype(jnp.float32))            # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k expert choice per token
    gate_vals, expert_idx = jax.lax.top_k(probs, top_k)   # [T, K]
    gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)

    # slot assignment: position of each (token, k) within its expert's
    # capacity buffer, computed with a cumsum over the one-hot choices
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [T, K, E]
    # priority: k=0 choices first, then k=1, preserving token order
    flat = onehot.transpose(1, 0, 2).reshape(top_k * n_tokens, e)
    pos = jnp.cumsum(flat, axis=0) - flat                 # [K*T, E]
    pos = pos.reshape(top_k, n_tokens, e).transpose(1, 0, 2)  # [T, K, E]
    slot = (pos * onehot).sum(-1)                          # [T, K]
    fits = slot < capacity
    gate_vals = gate_vals * fits                           # drop overflow

    # dispatch tensor [T, E, C]: token t → (expert, slot)
    dispatch = (onehot[..., None]
                * jax.nn.one_hot(slot, capacity,
                                 dtype=jnp.float32)[:, :, None, :]
                * fits[..., None, None]).sum(1)            # [T, E, C]
    combine = (dispatch
               * (gate_vals[:, :, None, None] * onehot[..., None])
               .sum(1))                                    # [T, E, C]

    dd = dispatch.astype(dtype)
    expert_in = jnp.einsum("tec,td->ecd", dd, xf.astype(dtype))
    up = jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(dtype))
    gate = jnp.einsum("ecd,edf->ecf", expert_in, w_gate.astype(dtype))
    act = jax.nn.silu(gate) * up
    expert_out = jnp.einsum("ecf,efd->ecd", act, w_down.astype(dtype))
    out = jnp.einsum("tec,ecd->td", combine.astype(dtype), expert_out)

    # Switch load-balance aux loss: E * sum_e(fraction_e * prob_mass_e)
    me = probs.mean(0)                                     # [E]
    ce = onehot[:, 0, :].mean(0)                           # top-1 fraction
    aux_loss = e * jnp.sum(me * ce)
    metrics = {
        "moe_aux_loss": aux_loss,
        "moe_overflow": 1.0 - fits.astype(jnp.float32).mean(),
    }
    return out.reshape(b, s, d).astype(dtype), metrics
