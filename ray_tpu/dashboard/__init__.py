"""Cluster dashboard: JSON API + single-page HTML overview on the head.

Reference analogue: ``dashboard/`` (dashboard head + its api modules —
nodes, actors, jobs, state). Scope here is the observability core:
cluster/node/actor/task/object/PG state, task+actor summaries, and a
Chrome-trace timeline export, served as `/api/*` JSON the same way the
reference's dashboard API serves its SPA — plus a dependency-free HTML
page instead of a React bundle.

Runs inside the head node process reading GCS/node state directly (no
client connection), so it keeps answering while drivers come and go.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .._private.http_util import HttpServerBase, JsonHandler
from ..state import api as state_api


class _HistoryCollector:
    """Ring-buffer time series of cluster utilization (reference:
    the dashboard's metrics time series, scoped to the capability: a
    bounded in-head history instead of a Prometheus+Grafana stack).
    Samples every ``period_s``; 600 samples x 2s = 20 minutes."""

    def __init__(self, node, period_s: float = 2.0, maxlen: int = 600):
        self._node = node
        self._period = period_s
        self.samples: deque = deque(maxlen=maxlen)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-dash-history")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._period):
            try:
                self.samples.append(self._sample())
            except Exception:   # noqa: BLE001 — a bad sample is a gap
                pass

    def _sample(self) -> dict:
        node = self._node
        total = node._cluster_info("resources_total") or {}
        avail = node._cluster_info("resources_available") or {}
        tasks = state_api.shape_tasks(node._state_query("tasks", None))
        by_state: dict = {}
        for t in tasks:
            by_state[t.get("state", "?")] =                 by_state.get(t.get("state", "?"), 0) + 1
        actors = state_api.shape_actors(
            node._state_query("actors", None))
        store = node.node_stats("store") or {}
        return {
            "ts": time.time(),
            "cpu_total": total.get("CPU", 0.0),
            "cpu_used": (total.get("CPU", 0.0)
                         - avail.get("CPU", 0.0)),
            "tpu_total": total.get("TPU", 0.0),
            "tpu_used": (total.get("TPU", 0.0)
                         - avail.get("TPU", 0.0)),
            "tasks_running": by_state.get("RUNNING", 0),
            "tasks_pending": (by_state.get("PENDING", 0)
                              + by_state.get("QUEUED", 0)),
            "tasks_finished": by_state.get("FINISHED", 0),
            "actors_alive": sum(1 for a in actors
                                if a.get("state") == "ALIVE"),
            "store_used_bytes": store.get("used_bytes", 0),
        }

_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.5rem; color: #222; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.4rem; }
 table { border-collapse: collapse; margin-top: .4rem; font-size: .85rem; }
 th, td { border: 1px solid #ccc; padding: .25rem .6rem; text-align: left; }
 th { background: #f3f3f3; }
 .pill { display: inline-block; padding: 0 .5rem; border-radius: 999px;
         background: #eef; margin-right: .4rem; }
 #err { color: #a00; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="cluster"></div><div id="err"></div>
<h2>Utilization history</h2>
<canvas id="hist" width="860" height="160"
 style="border:1px solid #ccc"></canvas>
<div id="histlegend" style="font-size:.8rem"></div>
<h2>Task drill-down</h2>
<input id="tid" placeholder="task id (hex or prefix)" size="36">
<button onclick="drill()">show timeline</button>
<table id="taskevents"></table>
<h2>Runtime metrics</h2>
<div style="font-size:.8rem">merged telemetry table
 (<a href="api/metrics">JSON</a> &middot;
  <a href="metrics">Prometheus</a>)</div>
<table id="metrics"></table>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Tasks (latest state)</h2><table id="tasks"></table>
<h2>Jobs</h2><table id="jobs"></table>
<script>
// all cluster-supplied strings (task/actor names, labels, entrypoints)
// are attacker-controlled: never reach innerHTML unescaped
function esc(s) {
  return String(s).replace(/[&<>"']/g, ch => ({"&": "&amp;", "<": "&lt;",
    ">": "&gt;", '"': "&quot;", "'": "&#39;"}[ch]));
}
function fill(id, rows, cols) {
  const t = document.getElementById(id);
  if (!rows || !rows.length) { t.innerHTML = "<tr><td>none</td></tr>"; return; }
  cols = cols || Object.keys(rows[0]);
  let h = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows.slice(-50))
    h += "<tr>" + cols.map(
      c => `<td>${esc(JSON.stringify(r[c]) ?? "")}</td>`).join("") + "</tr>";
  t.innerHTML = h;
}
async function refresh() {
  try {
    const c = await (await fetch("api/cluster")).json();
    document.getElementById("cluster").innerHTML =
      Object.entries(c.resources_total || {}).map(
        ([k, v]) => `<span class="pill">${esc(k)}: ` +
          `${esc((c.resources_available||{})[k] ?? "?")} / ` +
          `${esc(v)}</span>`).join("") +
      `<span class="pill">nodes: ${esc(c.num_nodes)}</span>` +
      `<span class="pill">mem used: ` +
      `${esc(((c.memory||{}).usage_fraction*100).toFixed(0))}%</span>`;
    fill("nodes", (await (await fetch("api/nodes")).json()).nodes);
    fill("actors", (await (await fetch("api/actors")).json()).actors);
    fill("tasks", (await (await fetch("api/tasks")).json()).tasks);
    fill("jobs", (await (await fetch("api/jobs")).json()).jobs);
    document.getElementById("err").textContent = "";
  } catch (e) { document.getElementById("err").textContent = String(e); }
}
function drawHistory(samples) {
  const cv = document.getElementById("hist"), ctx = cv.getContext("2d");
  ctx.clearRect(0, 0, cv.width, cv.height);
  if (!samples.length) return;
  const series = [
    ["cpu_used", "#c33", s => s.cpu_total ? s.cpu_used / s.cpu_total : 0],
    ["tasks_running", "#36c",
     s => s.tasks_running / Math.max(1, ...samples.map(
       x => x.tasks_running))],
    ["store_used", "#390",
     s => s.store_used_bytes / Math.max(1, ...samples.map(
       x => x.store_used_bytes))],
  ];
  for (const [name, color, f] of series) {
    ctx.strokeStyle = color; ctx.beginPath();
    samples.forEach((s, i) => {
      const x = i / Math.max(1, samples.length - 1) * (cv.width - 8) + 4;
      const y = cv.height - 6 - f(s) * (cv.height - 12);
      i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
    });
    ctx.stroke();
  }
  const span = ((samples[samples.length-1].ts - samples[0].ts) / 60)
    .toFixed(1);
  document.getElementById("histlegend").innerHTML =
    `<span style="color:#c33">cpu utilization</span> &middot; ` +
    `<span style="color:#36c">tasks running (rel)</span> &middot; ` +
    `<span style="color:#390">store used (rel)</span> &middot; ` +
    `window: ${esc(span)} min`;
}
async function drill() {
  const tid = document.getElementById("tid").value.trim();
  if (!tid) return;
  const r = await (await fetch("api/task/" +
    encodeURIComponent(tid))).json();
  fill("taskevents", r.events,
       ["timestamp", "state", "name", "node_id", "task_id"]);
}
async function refreshHist() {
  try {
    const h = await (await fetch("api/history")).json();
    drawHistory(h.samples || []);
  } catch (e) {}
}
async function refreshMetrics() {
  try {
    const m = await (await fetch("api/metrics")).json();
    const rows = (m.metrics || []).map(r => ({
      name: r.name, kind: r.kind,
      tags: Object.entries(r.tags || {}).map(
        ([k, v]) => `${k}=${v}`).join(","),
      value: r.kind === "histogram"
        ? `count=${r.count} mean=${r.count
            ? (r.sum / r.count).toFixed(4) : "-"}`
        : r.kind === "digest"
        ? `count=${r.count} p50=${(r.quantiles||{}).p50?.toFixed(4)
            } p99=${(r.quantiles||{}).p99?.toFixed(4)}`
        : r.value,
    }));
    fill("metrics", rows, ["name", "kind", "tags", "value"]);
  } catch (e) {}
}
refresh(); setInterval(refresh, 2000);
refreshHist(); setInterval(refreshHist, 4000);
refreshMetrics(); setInterval(refreshMetrics, 4000);
</script></body></html>
"""


class _Handler(JsonHandler):
    node = None           # NodeService, set by server factory
    job_manager = None    # optional JobManager
    history = None        # _HistoryCollector, set by server factory

    def do_GET(self):   # noqa: C901 — flat route table
        path = self.path.split("?", 1)[0].rstrip("/")
        node = self.node
        try:
            if path in ("", "/", "/index.html"):
                return self._html(_HTML)
            if path == "/api/cluster":
                mem = node.node_stats("memory") or {}
                nodes = node._cluster_info("nodes") or []
                return self._json(200, {
                    "num_nodes": sum(1 for n in nodes if n["alive"]),
                    "resources_total": node._cluster_info("resources_total"),
                    "resources_available":
                        node._cluster_info("resources_available"),
                    "memory": mem,
                })
            if path == "/api/nodes":
                return self._json(200, {"nodes": state_api.shape_nodes(
                    node._cluster_info("nodes"))})
            if path == "/api/workers":
                return self._json(200,
                                  {"workers": node._cluster_info("workers")})
            if path == "/api/actors":
                return self._json(200, {"actors": state_api.shape_actors(
                    node._state_query("actors", None))})
            if path == "/api/tasks":
                return self._json(200, {"tasks": state_api.shape_tasks(
                    node._state_query("tasks", None))})
            if path == "/api/objects":
                return self._json(200, {"objects": state_api.shape_objects(
                    node._state_query("objects", None))})
            if path == "/api/memory":
                # memory introspection plane: per-object provenance +
                # ref types, grouped callsite rollup, leak findings
                mem = node._state_query("memory", None) or {}
                rows = state_api.shape_objects(mem.get("objects"))
                return self._json(200, {
                    "summary": state_api.summarize_memory_rows(rows),
                    "objects": rows[:200],
                    "leaks": state_api.shape_leaks(mem.get("leaks")),
                    "stores": mem.get("stores") or {},
                })
            if path == "/api/placement_groups":
                return self._json(200, {
                    "placement_groups": state_api.shape_placement_groups(
                        node._state_query("placement_groups", None))})
            if path == "/api/summary":
                tasks = state_api.shape_tasks(
                    node._state_query("tasks", None))
                actors = state_api.shape_actors(
                    node._state_query("actors", None))
                return self._json(200, {
                    "tasks": state_api.summarize_task_rows(tasks),
                    "actors": state_api.summarize_actor_rows(actors)})
            if path == "/api/history":
                hist = getattr(self, "history", None)
                return self._json(200, {
                    "samples": list(hist.samples) if hist else []})
            if path == "/api/metrics":
                snap = node._state_query("metrics", None) or {}
                return self._json(200, {
                    "metrics": state_api.shape_metrics(snap),
                    "dropped_series": snap.get("dropped_series", 0)})
            if path == "/api/metrics/history":
                # windowed retention-ring series (?name=&window=&step=)
                from urllib.parse import parse_qs
                qs = parse_qs(self.path.split("?", 1)[1]
                              if "?" in self.path else "")

                def _num(key):
                    try:
                        return float(qs[key][0]) if key in qs else None
                    except (ValueError, IndexError):
                        return None

                return self._json(200, {
                    "history": node._state_query("metrics_history", {
                        "name": (qs.get("name") or [None])[0],
                        "window": _num("window"),
                        "step": _num("step"),
                    }) or {}})
            if path == "/api/lifecycle":
                # node/actor/PG state transitions retained past death
                return self._json(200, {
                    "lifecycle": node._state_query("lifecycle", None)
                    or [],
                    "events_stats": node._state_query("events_stats",
                                                      None) or {}})
            if path == "/metrics":
                # Prometheus scrape surface on the dashboard port (same
                # merged table the JSON endpoint serves)
                from ..util.metrics import format_prometheus
                body = format_prometheus(
                    node._state_query("metrics", None) or {},
                    include_exemplars=False).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if path == "/api/serve":
                # serving health plane: per-deployment latency/queue
                # percentiles (streaming digests), queue depth, error
                # rate and the replica table — shaped from the head's
                # merged metrics table (no client needed)
                return self._json(200, {
                    "serve": state_api.shape_serve_health(
                        node._state_query("metrics", None))})
            if path == "/api/stacks":
                # on-demand cluster thread dump (the `rtpu stack`
                # surface); handler threads may block for the fan-out
                return self._json(200,
                                  {"stacks": node.cluster_stacks(3.0)})
            if path == "/api/collectives":
                # flight-recorder surface (the `rtpu coll-debug`
                # equivalent): in-flight watermarks + hang verdicts;
                # handler threads may block for the fan-out
                return self._json(
                    200, {"collectives": node.collective_health(2.0)})
            if path.startswith("/api/task/"):
                # drill-down: every recorded state transition of one
                # task (id or unique hex prefix), time-ordered
                tid = path.rsplit("/", 1)[1]
                events = []
                for ev in node._state_query("tasks", None) or []:
                    ev_hex = getattr(ev.get("task_id"), "hex",
                                     lambda: str(ev.get("task_id")))()
                    if ev_hex.startswith(tid):
                        events.append({
                            "task_id": ev_hex,
                            "name": ev.get("name"),
                            "state": ev.get("state"),
                            "node_id": (ev["node_id"].hex()
                                        if ev.get("node_id") else None),
                            "timestamp": ev.get("timestamp"),
                        })
                events.sort(key=lambda e: e["timestamp"] or 0)
                return self._json(200, {"task_id": tid,
                                        "events": events})
            if path == "/api/jobs":
                if self.job_manager is None:
                    return self._json(200, {"jobs": []})
                return self._json(200,
                                  {"jobs": self.job_manager.list_jobs()})
            return self._json(404, {"error": f"no route {self.path}"})
        except Exception as e:   # noqa: BLE001 — API surface
            return self._json(500, {"error": str(e)})


class DashboardServer(HttpServerBase):
    """HTTP server bound to a NodeService (start on the head)."""

    thread_name = "rtpu-dashboard"

    # loopback by default: full cluster state should not be readable by
    # any network peer without an explicit opt-in (--http-host=0.0.0.0)
    def __init__(self, node, job_manager=None, host: str = "127.0.0.1",
                 port: int = 0):
        self.history = _HistoryCollector(node)
        super().__init__(_Handler, host=host, port=port,
                         node=node, job_manager=job_manager,
                         history=self.history)

    def stop(self) -> None:
        self.history.stop()
        super().stop()
