"""Cluster dashboard: JSON API + single-page HTML overview on the head.

Reference analogue: ``dashboard/`` (dashboard head + its api modules —
nodes, actors, jobs, state). Scope here is the observability core:
cluster/node/actor/task/object/PG state, task+actor summaries, and a
Chrome-trace timeline export, served as `/api/*` JSON the same way the
reference's dashboard API serves its SPA — plus a dependency-free HTML
page instead of a React bundle.

Runs inside the head node process reading GCS/node state directly (no
client connection), so it keeps answering while drivers come and go.
"""

from __future__ import annotations

from .._private.http_util import HttpServerBase, JsonHandler
from ..state import api as state_api

_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.5rem; color: #222; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.4rem; }
 table { border-collapse: collapse; margin-top: .4rem; font-size: .85rem; }
 th, td { border: 1px solid #ccc; padding: .25rem .6rem; text-align: left; }
 th { background: #f3f3f3; }
 .pill { display: inline-block; padding: 0 .5rem; border-radius: 999px;
         background: #eef; margin-right: .4rem; }
 #err { color: #a00; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="cluster"></div><div id="err"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Tasks (latest state)</h2><table id="tasks"></table>
<h2>Jobs</h2><table id="jobs"></table>
<script>
// all cluster-supplied strings (task/actor names, labels, entrypoints)
// are attacker-controlled: never reach innerHTML unescaped
function esc(s) {
  return String(s).replace(/[&<>"']/g, ch => ({"&": "&amp;", "<": "&lt;",
    ">": "&gt;", '"': "&quot;", "'": "&#39;"}[ch]));
}
function fill(id, rows, cols) {
  const t = document.getElementById(id);
  if (!rows || !rows.length) { t.innerHTML = "<tr><td>none</td></tr>"; return; }
  cols = cols || Object.keys(rows[0]);
  let h = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows.slice(-50))
    h += "<tr>" + cols.map(
      c => `<td>${esc(JSON.stringify(r[c]) ?? "")}</td>`).join("") + "</tr>";
  t.innerHTML = h;
}
async function refresh() {
  try {
    const c = await (await fetch("api/cluster")).json();
    document.getElementById("cluster").innerHTML =
      Object.entries(c.resources_total || {}).map(
        ([k, v]) => `<span class="pill">${esc(k)}: ` +
          `${esc((c.resources_available||{})[k] ?? "?")} / ` +
          `${esc(v)}</span>`).join("") +
      `<span class="pill">nodes: ${esc(c.num_nodes)}</span>` +
      `<span class="pill">mem used: ` +
      `${esc(((c.memory||{}).usage_fraction*100).toFixed(0))}%</span>`;
    fill("nodes", (await (await fetch("api/nodes")).json()).nodes);
    fill("actors", (await (await fetch("api/actors")).json()).actors);
    fill("tasks", (await (await fetch("api/tasks")).json()).tasks);
    fill("jobs", (await (await fetch("api/jobs")).json()).jobs);
    document.getElementById("err").textContent = "";
  } catch (e) { document.getElementById("err").textContent = String(e); }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class _Handler(JsonHandler):
    node = None           # NodeService, set by server factory
    job_manager = None    # optional JobManager

    def do_GET(self):   # noqa: C901 — flat route table
        path = self.path.split("?", 1)[0].rstrip("/")
        node = self.node
        try:
            if path in ("", "/", "/index.html"):
                return self._html(_HTML)
            if path == "/api/cluster":
                mem = node.node_stats("memory") or {}
                nodes = node._cluster_info("nodes") or []
                return self._json(200, {
                    "num_nodes": sum(1 for n in nodes if n["alive"]),
                    "resources_total": node._cluster_info("resources_total"),
                    "resources_available":
                        node._cluster_info("resources_available"),
                    "memory": mem,
                })
            if path == "/api/nodes":
                return self._json(200, {"nodes": state_api.shape_nodes(
                    node._cluster_info("nodes"))})
            if path == "/api/workers":
                return self._json(200,
                                  {"workers": node._cluster_info("workers")})
            if path == "/api/actors":
                return self._json(200, {"actors": state_api.shape_actors(
                    node._state_query("actors", None))})
            if path == "/api/tasks":
                return self._json(200, {"tasks": state_api.shape_tasks(
                    node._state_query("tasks", None))})
            if path == "/api/objects":
                return self._json(200, {"objects": state_api.shape_objects(
                    node._state_query("objects", None))})
            if path == "/api/placement_groups":
                return self._json(200, {
                    "placement_groups": state_api.shape_placement_groups(
                        node._state_query("placement_groups", None))})
            if path == "/api/summary":
                tasks = state_api.shape_tasks(
                    node._state_query("tasks", None))
                actors = state_api.shape_actors(
                    node._state_query("actors", None))
                return self._json(200, {
                    "tasks": state_api.summarize_task_rows(tasks),
                    "actors": state_api.summarize_actor_rows(actors)})
            if path == "/api/jobs":
                if self.job_manager is None:
                    return self._json(200, {"jobs": []})
                return self._json(200,
                                  {"jobs": self.job_manager.list_jobs()})
            return self._json(404, {"error": f"no route {self.path}"})
        except Exception as e:   # noqa: BLE001 — API surface
            return self._json(500, {"error": str(e)})


class DashboardServer(HttpServerBase):
    """HTTP server bound to a NodeService (start on the head)."""

    thread_name = "rtpu-dashboard"

    # loopback by default: full cluster state should not be readable by
    # any network peer without an explicit opt-in (--http-host=0.0.0.0)
    def __init__(self, node, job_manager=None, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(_Handler, host=host, port=port,
                         node=node, job_manager=job_manager)
