"""ctypes bindings for the native runtime (``native/*.cpp``).

The shared library is built on demand with make (g++ is in the image;
pybind11 is not, so the ABI is plain C via ctypes). Everything degrades
gracefully: if the toolchain or build is unavailable, callers fall back
to pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from . import locksan

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_native", "libobject_arena.so")

_lib = None
_lib_lock = locksan.lock("native.lib")
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if os.path.isdir(_NATIVE_DIR):
            try:
                # inter-process flock: many workers may race the first
                # build; exactly one runs make, the rest wait on the lock.
                # make runs even when the .so exists — a stale build from
                # an older source (missing newer symbols) must be rebuilt,
                # and an up-to-date one is a no-op stat check.
                import fcntl
                lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
                with open(lock_path, "w") as lock_f:
                    fcntl.flock(lock_f, fcntl.LOCK_EX)
                    subprocess.run(["make", "-s"], cwd=_NATIVE_DIR,  # lint: allow-under-lock(one-time build; the lock is what makes exactly one thread run make)
                                   check=True, capture_output=True,
                                   timeout=120)
            except Exception:
                if not os.path.exists(_LIB_PATH):
                    _build_failed = True
                    return None
        elif not os.path.exists(_LIB_PATH):
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.arena_attach.restype = ctypes.c_void_p
        lib.arena_attach.argtypes = [ctypes.c_char_p]
        lib.arena_alloc.restype = ctypes.c_int64
        lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.arena_free.restype = ctypes.c_int
        lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.arena_base.restype = ctypes.POINTER(ctypes.c_ubyte)
        lib.arena_base.argtypes = [ctypes.c_void_p]
        lib.arena_capacity.restype = ctypes.c_uint64
        lib.arena_capacity.argtypes = [ctypes.c_void_p]
        lib.arena_used.restype = ctypes.c_uint64
        lib.arena_used.argtypes = [ctypes.c_void_p]
        lib.arena_num_blocks.restype = ctypes.c_uint64
        lib.arena_num_blocks.argtypes = [ctypes.c_void_p]
        lib.arena_close.restype = None
        lib.arena_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        try:
            # mapper refcounts; absent in .so builds from older sources
            # (refcount callers degrade to the time quarantine)
            for sym in ("arena_incref", "arena_decref", "arena_refcount"):
                fn = getattr(lib, sym)
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        except AttributeError:
            pass
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class _RefcountMixin:
    """Per-block mapper refcounts, shared by owner and reader handles.
    All three degrade to None/no-op on a library built from an older
    source (no arena_incref symbol)."""

    def incref(self, offset: int) -> Optional[int]:
        fn = getattr(self._lib, "arena_incref", None)
        if fn is None or not self._handle:
            return None
        n = fn(self._handle, offset)
        return None if n < 0 else n

    def decref(self, offset: int) -> Optional[int]:
        fn = getattr(self._lib, "arena_decref", None)
        if fn is None or not self._handle:
            return None
        n = fn(self._handle, offset)
        return None if n < 0 else n

    def refcount(self, offset: int) -> Optional[int]:
        fn = getattr(self._lib, "arena_refcount", None)
        if fn is None or not self._handle:
            return None
        n = fn(self._handle, offset)
        return None if n < 0 else n


class Arena(_RefcountMixin):
    """Owner-side arena (the node store process allocates; readers use
    ``ArenaReader``)."""

    def __init__(self, path: str, capacity: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native arena unavailable")
        self._lib = lib
        self.path = path
        self._handle = lib.arena_create(path.encode(), capacity)
        if not self._handle:
            raise RuntimeError(f"arena_create failed for {path}")
        self.capacity = lib.arena_capacity(self._handle)

    def alloc(self, size: int) -> Optional[int]:
        off = self._lib.arena_alloc(self._handle, size)
        return None if off < 0 else off

    def free(self, offset: int) -> None:
        self._lib.arena_free(self._handle, offset)

    def view(self, offset: int, size: int) -> memoryview:
        base = self._lib.arena_base(self._handle)
        addr = ctypes.addressof(base.contents) + offset
        return (ctypes.c_ubyte * size).from_address(addr)

    def buffer(self, offset: int, size: int) -> memoryview:
        return memoryview(self.view(offset, size)).cast("B")

    @property
    def used(self) -> int:
        return self._lib.arena_used(self._handle)

    @property
    def num_blocks(self) -> int:
        return self._lib.arena_num_blocks(self._handle)

    def close(self, unlink: bool = True) -> None:
        if self._handle:
            self._lib.arena_close(self._handle, 1 if unlink else 0)
            self._handle = None


class ArenaReader(_RefcountMixin):
    """Reader-side attachment (one mmap per process per arena)."""

    _cache: dict = {}
    _cache_lock = locksan.lock("native.arena_cache")

    def __init__(self, path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native arena unavailable")
        self._lib = lib
        self._handle = lib.arena_attach(path.encode())
        if not self._handle:
            raise RuntimeError(f"arena_attach failed for {path}")

    @classmethod
    def get(cls, path: str) -> "ArenaReader":
        with cls._cache_lock:
            reader = cls._cache.get(path)
            if reader is None:
                reader = cls(path)
                cls._cache[path] = reader
            return reader

    def buffer(self, offset: int, size: int) -> memoryview:
        base = self._lib.arena_base(self._handle)
        addr = ctypes.addressof(base.contents) + offset
        return memoryview((ctypes.c_ubyte * size).from_address(addr)) \
            .cast("B")

    def tracked_buffer(self, offset: int, size: int) -> memoryview:
        """Zero-copy view that holds a mapper reference on the block:
        increfs now, decrefs when the last derived view is collected
        (weakref.finalize on the backing ctypes array — every numpy
        view/memoryview slice keeps that array alive). The owner defers
        free/spill of the block while the count is nonzero, so user code
        can hold views indefinitely without a reuse-corruption window.
        Raises FileNotFoundError when the block was already freed (the
        meta was stale) — callers retry through a fresh GET exactly like
        a spilled-and-unlinked segment."""
        base = self._lib.arena_base(self._handle)
        addr = ctypes.addressof(base.contents) + offset
        arr = (ctypes.c_ubyte * size).from_address(addr)
        if getattr(self._lib, "arena_incref", None) is not None:
            if self.incref(offset) is None:
                raise FileNotFoundError(
                    f"arena block @{offset} already freed")
            import weakref
            weakref.finalize(arr, self.decref, offset)
        return memoryview(arr).cast("B")

    def close(self) -> None:
        if self._handle:
            self._lib.arena_close(self._handle, 0)
            self._handle = None
