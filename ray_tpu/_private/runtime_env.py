"""Runtime environments: per-task/actor worker environments.

Reference: ``python/ray/_private/runtime_env/`` — envs are built by a
per-node agent, URI-cached, and the raylet's WorkerPool keys workers by
(language, runtime env) so tasks only run on workers built for their
env (``worker_pool.h:152``). Same design here, minus the network-bound
builders: ``env_vars``, ``working_dir`` and ``py_modules`` are staged
locally and baked into the worker at spawn; ``pip`` builds a cached
virtualenv the worker is exec'd into (``worker_bootstrap.py``);
``conda``/``container`` are rejected up-front (building those needs
infrastructure a hermetic image doesn't carry).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip"}
_REJECTED = {"conda", "container", "uv"}


def validate(runtime_env: Optional[Dict[str, Any]]) -> Optional[dict]:
    if not runtime_env:
        return None
    unknown = set(runtime_env) - _SUPPORTED - _REJECTED
    if unknown:
        raise ValueError(f"unknown runtime_env keys: {sorted(unknown)}")
    bad = set(runtime_env) & _REJECTED
    if bad:
        raise ValueError(
            f"runtime_env keys {sorted(bad)} need network-built "
            "environments, which this runtime does not support; ship a "
            "hermetic image and use env_vars/working_dir/py_modules/pip")
    env = dict(runtime_env)
    if "pip" in env:
        env["pip"] = _normalize_pip(env["pip"])
    if "env_vars" in env:
        env["env_vars"] = {str(k): str(v)
                           for k, v in env["env_vars"].items()}
    if "working_dir" in env:
        wd = os.path.abspath(env["working_dir"])
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd} is not a directory")
        env["working_dir"] = wd
    if "py_modules" in env:
        env["py_modules"] = [os.path.abspath(p)
                             for p in env["py_modules"]]
    return env


def _normalize_pip(pip: Any) -> dict:
    """Canonical form: {"packages": [...], "options": [...]}.

    Accepts the reference's shapes — a plain list of requirement
    specifiers, or a dict with ``packages`` (+ optional
    ``pip_install_options``, e.g. ``["--no-index"]`` for offline
    wheel-path installs).
    """
    if isinstance(pip, (list, tuple)):
        pip = {"packages": list(pip)}
    elif isinstance(pip, dict):
        unknown = set(pip) - {"packages", "pip_install_options", "options"}
        if unknown:
            raise ValueError(
                f"unknown pip keys: {sorted(unknown)} (supported: "
                f"packages, pip_install_options)")
        pkgs = pip.get("packages", [])
        opts = pip.get("pip_install_options", ()) or pip.get("options", ())
        if isinstance(pkgs, str) or isinstance(opts, str):
            raise ValueError(
                "pip packages/options must be lists of strings, not a "
                "bare string (a string would be split per character)")
        pip = {"packages": list(pkgs), "options": list(opts)}
    else:
        raise ValueError(f"pip must be a list or dict, got {type(pip)}")
    pip.setdefault("options", [])
    for item in pip["packages"] + pip["options"]:
        if not isinstance(item, str):
            raise ValueError(f"pip entries must be strings, got {item!r}")
    return pip


def pip_spec(runtime_env: Optional[dict]) -> Optional[dict]:
    """The bootstrap payload for a pip env: packages, options, and the
    cache key the venv directory is named by.

    Packages that are local paths (wheels/sdists) contribute their
    mtime+size to the key, so rebuilding a wheel at the same path gets a
    fresh venv — the same reason working_dir staging keys on tree mtime.
    """
    if not runtime_env or "pip" not in runtime_env:
        return None
    pip = runtime_env["pip"]
    local_state = []
    for pkg in pip["packages"]:
        path = pkg.split("#", 1)[0].removeprefix("file://")
        if os.path.exists(path):
            st = os.stat(path)
            local_state.append((pkg, st.st_mtime, st.st_size))
    return {"key": env_key({"pip": pip, "local": local_state}),
            "packages": pip["packages"], "options": pip["options"]}


def env_key(runtime_env: Optional[dict]) -> str:
    """Stable hash keying the worker pool (reference: runtime-env URI)."""
    if not runtime_env:
        return ""
    return hashlib.sha256(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:16]


def stage(runtime_env: Optional[dict], session_dir: str
          ) -> Tuple[Dict[str, str], Optional[str]]:
    """Prepare a worker spawn environment: returns (env_overrides, cwd).

    working_dir is snapshotted into the session dir (so later edits to
    the source tree don't leak into running workers — the reference
    zips to GCS for the same reason) and cached by content key.
    """
    if not runtime_env:
        return {}, None
    overrides: Dict[str, str] = dict(runtime_env.get("env_vars", {}))
    cwd = None
    py_paths = []
    wd = runtime_env.get("working_dir")
    if wd:
        key = env_key({"working_dir": wd,
                       "mtime": _tree_mtime(wd)})
        target = os.path.join(session_dir, "runtime_envs", key)
        if not os.path.isdir(target):
            os.makedirs(os.path.dirname(target), exist_ok=True)
            tmp = target + ".tmp"
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            shutil.copytree(wd, tmp)
            os.replace(tmp, target)
        cwd = target
        py_paths.append(target)
    for mod in runtime_env.get("py_modules", ()):
        py_paths.append(mod if os.path.isdir(mod)
                        else os.path.dirname(mod))
    if py_paths:
        existing = overrides.get("PYTHONPATH",
                                 os.environ.get("PYTHONPATH", ""))
        overrides["PYTHONPATH"] = os.pathsep.join(
            py_paths + ([existing] if existing else []))
    return overrides, cwd


def _tree_mtime(path: str) -> float:
    latest = os.path.getmtime(path)
    for root, _, files in os.walk(path):
        for f in files:
            try:
                latest = max(latest,
                             os.path.getmtime(os.path.join(root, f)))
            except OSError:
                pass
    return latest
