"""Control-plane metrics history: fixed-memory multi-resolution rings.

The merge table in ``gcs.py`` answers "what is true right now"; this
module gives it a time axis (reference analogue: the dashboard's
Prometheus-backed time series and the GCS task-event time dimension,
scoped to the capability — a bounded in-head retention ring instead of
an external TSDB). Every history tick the plane appends one compact
*frame* — cumulative counter values, latest gauges, histogram
count/sum, and the *interval* quantile digest accumulated since the
previous frame — into a ladder of resolution levels (e.g. 1s×120 /
10s×180 / 60s×240): recent history is fine-grained, older history
coarsens instead of vanishing. Memory is doubly bounded: per-level slot
caps plus a hard byte cap (oldest finest frames evict first).

Counters are stored cumulatively, so downsampling is sampling and
``rate()``/``delta()`` shaping is an exact diff at any resolution.
Interval digests merge losslessly (t-digest payload fold), so a coarse
frame's p95 is the true p95 of its whole interval, not a quantile of
quantiles.

Everything here is pure data structure + pure functions: the plane
calls it under its own lock, and the SAME query/shaping/trend code runs
offline against a bundle dump (``rtpu autopsy``) with no live cluster.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from . import fieldsan
from . import telemetry

M_HISTORY_BYTES = telemetry.define(
    "gauge", "rtpu_metrics_history_bytes",
    "Estimated bytes held by the control-plane metrics-history rings "
    "(sampled at each history tick; bounded by "
    "metrics_history_max_bytes)")

# centroid cap of the per-frame interval digests: coarser than the live
# digests' cap — history trades tail precision for 120+ retained frames
_FRAME_DIGEST_CENTROIDS = 32

# per-entry byte estimates for the hard cap (key tuples are shared with
# the live merge table, so a frame's marginal cost is the value cells)
_B_FRAME = 96
_B_SCALAR = 56          # dict entry + float
_B_PAIR = 72            # dict entry + 2-tuple of floats
_B_DIGEST_BASE = 120
_B_CENTROID = 18


class _Frame:
    __slots__ = ("ts", "counters", "gauges", "hists", "digests", "nbytes")

    def __init__(self, ts: float, counters: dict, gauges: dict,
                 hists: dict, digests: dict):
        self.ts = ts
        self.counters = counters        # key -> cumulative float
        self.gauges = gauges            # key -> float
        self.hists = hists              # key -> (count, sum)
        self.digests = digests          # key -> interval digest payload
        self.nbytes = (_B_FRAME
                       + _B_SCALAR * (len(counters) + len(gauges))
                       + _B_PAIR * len(hists)
                       + sum(_B_DIGEST_BASE + _B_CENTROID
                             * len(d.get("centroids") or ())
                             for d in digests.values()))


@fieldsan.guarded
class _Level:
    __slots__ = ("step", "capacity", "frames", "last_ts",
                 "pending_digests")

    def __init__(self, step: float, capacity: int):
        self.step = float(step)
        self.capacity = int(capacity)
        self.frames: deque = deque()
        self.last_ts = 0.0
        # interval digest payloads merged since this level's last frame
        self.pending_digests: Dict[tuple, dict] = {}


def _parse_resolutions(steps: str, capacity: int) -> List[Tuple[float, int]]:
    """``metrics_history_steps`` x ``metrics_history_capacity`` -> the
    level ladder. Level i keeps ``capacity * (2 + i) / 2`` slots, so the
    shipped 120 with steps 1,10,60 yields the 1s×120 / 10s×180 / 60s×240
    ladder; malformed knobs degrade to the default ladder rather than
    disabling retention."""
    try:
        parsed = [float(s) for s in steps.split(",") if s.strip()]
        parsed = [s for s in parsed if s > 0]
    except ValueError:
        parsed = []
    if not parsed:
        parsed = [1.0, 10.0, 60.0]
    parsed.sort()
    return [(s, max(1, capacity * (2 + i) // 2))
            for i, s in enumerate(parsed)]


@fieldsan.guarded
class MetricsHistory:
    """Multi-resolution frame rings. NOT internally locked — the owning
    control plane serializes access under its own lock."""

    def __init__(self, capacity: int, steps: str, max_bytes: int):
        self.enabled = capacity > 0
        self.max_bytes = int(max_bytes)
        self.levels = [(_Level(s, c))
                       for s, c in _parse_resolutions(steps, capacity)]
        self.total_bytes = 0
        self.frames_evicted = 0

    # ------------------------------------------------------------ record
    # concurrency: requires(gcs.plane)
    def record(self, ts: float, counters: dict, gauges: dict,
               hists: dict, interval_digests: dict) -> int:
        """Append one snapshot instant. ``counters``/``gauges``/``hists``
        are the merge table's CURRENT values (cumulative — sampling them
        at any cadence is exact); ``interval_digests`` are the digest
        deltas folded since the previous record call (each level merges
        them until its own frame is due). Returns the estimated total
        bytes after the append."""
        if not self.enabled:
            return 0
        for level in self.levels:
            for key, payload in interval_digests.items():
                cur = level.pending_digests.get(key)
                level.pending_digests[key] = (
                    telemetry.merge_digest_payloads(cur, payload)
                    if cur else dict(payload))
            if ts - level.last_ts < level.step:
                continue
            level.last_ts = ts
            digests = {
                key: _recompress(payload)
                for key, payload in level.pending_digests.items()
                if payload.get("count")}
            level.pending_digests = {}
            frame = _Frame(ts, dict(counters), dict(gauges),
                           dict(hists), digests)
            level.frames.append(frame)
            self.total_bytes += frame.nbytes
            while len(level.frames) > level.capacity:
                self._evict(level)
        # hard byte cap: evict oldest FINEST frames first (most
        # numerous, cheapest loss), walking coarser only when a level
        # runs dry — retention degrades, it never blows the budget
        while self.total_bytes > self.max_bytes:
            level = next((lv for lv in self.levels if lv.frames), None)
            if level is None:
                break
            self._evict(level)
        return self.total_bytes

    # concurrency: requires(gcs.plane)
    def _evict(self, level: _Level) -> None:
        frame = level.frames.popleft()
        self.total_bytes -= frame.nbytes
        self.frames_evicted += 1

    # ------------------------------------------------------------- query
    def level_snapshot(self) -> List[tuple]:
        """Cheap ``(step, capacity, [frame refs])`` copy — take this
        under the OWNER'S lock, then run ``query_levels``/``dump_levels``
        outside it: frames are immutable once appended, so the only
        thing the lock must protect is the deque itself. Converting/
        filtering hundreds of frames under the control-plane lock would
        stall scheduling for every dashboard/doctor query."""
        return [(lv.step, lv.capacity, list(lv.frames))
                for lv in self.levels]

    def query(self, name: Optional[str] = None,
              tags: Optional[dict] = None,
              window: Optional[float] = None,
              step: Optional[float] = None) -> dict:
        """Aligned windowed series (see ``query_frames``), picking the
        finest level that covers ``window`` (or honors ``step``)."""
        return query_levels(self.level_snapshot(), self.enabled,
                            name=name, tags=tags, window=window,
                            step=step)

    # -------------------------------------------------------------- dump
    def dump(self) -> dict:
        """Whole-ring JSON-able dump for debug bundles (see
        ``dump_levels`` for the lock-free half)."""
        return dump_levels(self.level_snapshot(), self.enabled,
                           self.total_bytes, self.frames_evicted)


def query_levels(snapshot: List[tuple], enabled: bool,
                 name: Optional[str] = None,
                 tags: Optional[dict] = None,
                 window: Optional[float] = None,
                 step: Optional[float] = None) -> dict:
    """Pure windowed query over a ``level_snapshot``: pick the finest
    level covering ``window`` (or honoring ``step``), then convert ONLY
    the matching entries of the in-window frames."""
    if not enabled or not snapshot:
        return {"series": [], "step_s": 0.0, "window_s": window or 0.0,
                "enabled": False}
    now = max((frames[-1].ts for _s, _c, frames in snapshot if frames),
              default=0.0)
    window = float(window) if window else snapshot[0][0] * snapshot[0][1]
    pick = None
    for lstep, cap, frames in snapshot:
        if step:
            # honor an explicit step: the finest level at/above it
            if lstep >= step:
                pick = (lstep, cap, frames)
                break
            continue
        if frames and now - frames[0].ts >= window * 0.8:
            pick = (lstep, cap, frames)
            break
        if lstep * cap >= window:
            pick = (lstep, cap, frames)
            break
    if pick is None:
        pick = snapshot[-1]
    lstep, _cap, frames = pick
    in_window = [f for f in frames if f.ts >= now - window]
    out = query_frames(_frames_jsonable(in_window, name=name),
                       name=name, tags=tags)
    out.update({"step_s": lstep, "window_s": window, "now": now,
                "enabled": True})
    return out


def dump_levels(snapshot: List[tuple], enabled: bool,
                total_bytes: int, frames_evicted: int) -> dict:
    """JSON-able whole-ring dump from a ``level_snapshot`` (run outside
    the owner's lock), replayed offline by ``query_dump``."""
    return {
        "enabled": enabled,
        "total_bytes": total_bytes,
        "frames_evicted": frames_evicted,
        "levels": [{
            "step_s": lstep,
            "capacity": cap,
            "frames": _frames_jsonable(frames),
        } for lstep, cap, frames in snapshot],
    }


def _recompress(payload: dict) -> dict:
    cents = payload.get("centroids") or []
    if len(cents) <= 2 * _FRAME_DIGEST_CENTROIDS:
        return dict(payload)
    out = dict(payload)
    out["centroids"] = telemetry.compress_centroids(
        [list(c) for c in cents], _FRAME_DIGEST_CENTROIDS)
    return out


def _frames_jsonable(frames, name: Optional[str] = None) -> List[dict]:
    """Tuple-keyed frames -> JSON-able rows ({"name", "tags"} keyed).
    ``name`` filters DURING conversion: a single-metric query over a
    full window must not materialize every other series' rows."""
    out = []
    for f in frames:
        out.append({
            "ts": f.ts,
            "counters": [[k[0], list(k[1]), v]
                         for k, v in f.counters.items()
                         if name is None or k[0] == name],
            "gauges": [[k[0], list(k[1]), v] for k, v in f.gauges.items()
                       if name is None or k[0] == name],
            "hists": [[k[0], list(k[1]), list(v)]
                      for k, v in f.hists.items()
                      if name is None or k[0] == name],
            "digests": [[k[0], list(k[1]), dict(d)]
                        for k, d in f.digests.items()
                        if name is None or k[0] == name],
        })
    return out


# --------------------------------------------------------------- queries
# Pure functions over JSON-able frame lists: the live plane AND the
# offline bundle replay (``rtpu autopsy``) share them verbatim.

def _tags_match(row_tags: list, want: Optional[dict]) -> bool:
    if not want:
        return True
    have = {str(k): str(v) for k, v in row_tags}
    return all(have.get(str(k)) == str(v) for k, v in want.items())


def query_frames(frames: List[dict], name: Optional[str] = None,
                 tags: Optional[dict] = None) -> dict:
    """Frames -> per-series point lists. Digest points carry derived
    quantiles (p50/p95/p99), count and mean of the frame's INTERVAL;
    histogram points carry (count, sum); counter/gauge points the
    value."""
    series: Dict[tuple, dict] = {}

    def ent(metric: str, row_tags: list, kind: str) -> Optional[dict]:
        if name is not None and metric != name:
            return None
        if not _tags_match(row_tags, tags):
            return None
        key = (metric, tuple(tuple(p) for p in row_tags))
        s = series.get(key)
        if s is None:
            s = series[key] = {"name": metric,
                               "tags": {str(k): str(v)
                                        for k, v in row_tags},
                               "kind": kind, "points": []}
        return s

    for f in frames:
        ts = f["ts"]
        for metric, row_tags, value in f.get("counters") or ():
            s = ent(metric, row_tags, "counter")
            if s is not None:
                s["points"].append([ts, value])
        for metric, row_tags, value in f.get("gauges") or ():
            s = ent(metric, row_tags, "gauge")
            if s is not None:
                s["points"].append([ts, value])
        for metric, row_tags, cs in f.get("hists") or ():
            s = ent(metric, row_tags, "histogram")
            if s is not None:
                s["points"].append([ts, {"count": cs[0], "sum": cs[1]}])
        for metric, row_tags, d in f.get("digests") or ():
            s = ent(metric, row_tags, "digest")
            if s is not None:
                cnt = d.get("count") or 0
                s["points"].append([ts, {
                    "p50": telemetry.digest_quantile(d, 0.50),
                    "p95": telemetry.digest_quantile(d, 0.95),
                    "p99": telemetry.digest_quantile(d, 0.99),
                    "count": cnt,
                    "mean": (d.get("sum", 0.0) / cnt) if cnt else 0.0,
                }])
    return {"series": sorted(series.values(),
                             key=lambda s: (s["name"],
                                            sorted(s["tags"].items())))}


def query_dump(dump: dict, name: Optional[str] = None,
               tags: Optional[dict] = None,
               window: Optional[float] = None,
               step: Optional[float] = None) -> dict:
    """Offline twin of ``MetricsHistory.query`` over a bundle dump."""
    levels = dump.get("levels") or []
    if not levels:
        return {"series": [], "step_s": 0.0, "window_s": window or 0.0,
                "enabled": bool(dump.get("enabled"))}
    now = max((lv["frames"][-1]["ts"] for lv in levels if lv["frames"]),
              default=0.0)
    window = float(window) if window else (levels[0]["step_s"]
                                           * levels[0]["capacity"])
    pick = None
    for lv in levels:
        if step and lv["step_s"] >= step:
            pick = lv
            break
        if not step:
            frames = lv["frames"]
            if frames and now - frames[0]["ts"] >= window * 0.8:
                pick = lv
                break
            if lv["step_s"] * lv["capacity"] >= window:
                pick = lv
                break
    if pick is None:
        pick = levels[-1]
    frames = [f for f in pick["frames"] if f["ts"] >= now - window]
    out = query_frames(frames, name=name, tags=tags)
    out.update({"step_s": pick["step_s"], "window_s": window, "now": now,
                "enabled": bool(dump.get("enabled", True))})
    return out


# --------------------------------------------------------------- shaping

def shape_points(points: List[list], shape: str,
                 field: Optional[str] = None) -> List[list]:
    """``rate`` / ``delta`` shaping so cumulative counters become
    usable throughput curves. ``field`` picks a sub-field of dict-valued
    points (histogram count/sum, digest count). ``value`` returns the
    (sub-)values unchanged. Rates clamp negative diffs to 0 — a counter
    reset (plane restart) must not render as negative throughput."""
    vals = []
    for ts, v in points:
        if isinstance(v, dict):
            v = v.get(field or "count", 0.0)
        vals.append([ts, float(v)])
    if shape in (None, "value"):
        return vals
    out = []
    for (t0, v0), (t1, v1) in zip(vals, vals[1:]):
        d = max(0.0, v1 - v0)
        if shape == "delta":
            out.append([t1, d])
        else:   # rate
            dt = max(t1 - t0, 1e-9)
            out.append([t1, d / dt])
    return out


def _head_tail(points: List[list], frac: float = 1.0 / 3.0
               ) -> Tuple[float, float]:
    """Mean of the first vs last ``frac`` of a numeric point list."""
    if not points:
        return 0.0, 0.0
    n = max(1, int(len(points) * frac))
    head = [p[1] for p in points[:n]]
    tail = [p[1] for p in points[-n:]]
    return sum(head) / len(head), sum(tail) / len(tail)


def _num_points(s: dict, field: Optional[str] = None) -> List[list]:
    out = []
    for ts, v in s["points"]:
        if isinstance(v, dict):
            v = v.get(field or "count", 0.0)
        out.append([ts, float(v)])
    return out


# ---------------------------------------------------------------- trends
# The doctor's watchlist: curated movements with cluster meaning. Each
# record: {"metric", "tags", "kind", "head", "tail", "ratio",
# "window_s", "severity", "message"}.

_RISING_GAUGES = {
    "rtpu_object_leaked_objects":
        "leaked objects rising — see `rtpu memory` / state.memory_summary()",
    "rtpu_scheduler_pending_tasks":
        "pending-task queue deepening",
    "rtpu_serve_replica_queue_depth":
        "serve replica queue depth rising",
    "rtpu_object_store_fill_ratio":
        "object store filling",
    "rtpu_collective_inflight_chunks":
        "undelivered collective chunks accumulating",
}

_RISING_DIGEST_P95 = {
    "rtpu_serve_queue_wait_digest_seconds": "queue_wait p95",
    "rtpu_serve_request_latency_digest_seconds": "latency p95",
}


def _fmt_tags(tags: dict) -> str:
    if not tags:
        return ""
    inner = ",".join(f"{k}={v}" for k, v in sorted(tags.items()))
    return "{" + inner + "}"


def compute_trends(result: dict, min_ratio: float = 2.0) -> List[dict]:
    """Head-vs-tail movement detection over one windowed query result
    (ALL series). Pure — the live doctor and the offline autopsy feed
    it the same shape. Conservative by design: only the curated
    watchlist plus the idle-node-while-queueing join can fire, each
    with a ratio floor AND an absolute floor, so a quiet cluster yields
    an empty list rather than noise."""
    out: List[dict] = []
    window = round(float(result.get("window_s") or 0.0))
    series = result.get("series") or []
    for s in series:
        name, tags = s["name"], s["tags"]
        if s["kind"] == "gauge" and name in _RISING_GAUGES:
            head, tail = _head_tail(_num_points(s))
            floor = 0.005 if name.endswith("_ratio") else 0.5
            if tail < floor or tail < min_ratio * max(head, floor / 10):
                continue
            ratio = tail / max(head, 1e-9)
            out.append({
                "metric": name, "tags": tags, "kind": "rising",
                "head": round(head, 4), "tail": round(tail, 4),
                "ratio": round(min(ratio, 999.0), 2),
                "window_s": window, "severity": "warn",
                "message": (f"{name}{_fmt_tags(tags)} "
                            f"{_RISING_GAUGES[name]}: "
                            f"{head:g} -> {tail:g} over {window}s"),
            })
        elif s["kind"] == "digest" and name in _RISING_DIGEST_P95:
            pts = [[ts, v.get("p95", 0.0)] for ts, v in s["points"]
                   if isinstance(v, dict) and v.get("count")]
            head, tail = _head_tail(pts)
            if tail < 0.001 or head <= 0 or tail < min_ratio * head:
                continue
            label = _RISING_DIGEST_P95[name]
            where = tags.get("deployment")
            out.append({
                "metric": name, "tags": tags, "kind": "rising",
                "head": round(head, 5), "tail": round(tail, 5),
                "ratio": round(tail / head, 2),
                "window_s": window, "severity": "warn",
                "message": (f"{label} {tail / head:.1f}x over {window}s"
                            + (f" on deployment {where!r}" if where
                               else "")
                            + f" ({head * 1000:.1f}ms -> "
                              f"{tail * 1000:.1f}ms)"),
            })
        elif (s["kind"] == "counter"
              and name == "rtpu_serve_requests_total"
              and tags.get("status") == "error"):
            rate_pts = shape_points(s["points"], "rate")
            head, tail = _head_tail(rate_pts)
            if tail < 0.2 or tail < min_ratio * max(head, 0.02):
                continue
            out.append({
                "metric": name, "tags": tags, "kind": "rising",
                "head": round(head, 3), "tail": round(tail, 3),
                "ratio": round(tail / max(head, 1e-9), 2),
                "window_s": window, "severity": "warn",
                "message": (f"serve error rate rising on deployment "
                            f"{tags.get('deployment')!r}: "
                            f"{head:.2f}/s -> {tail:.2f}/s over "
                            f"{window}s"),
            })
    out.extend(_idle_node_trends(series, window))
    out.sort(key=lambda r: (-r.get("ratio", 0.0), r["metric"]))
    return out


def _idle_node_trends(series: List[dict], window: int) -> List[dict]:
    """Cross-series join: a node that dispatched NOTHING over the
    window while tasks sit queued somewhere is wasted capacity worth a
    name ("node N idle Ns while tasks queue")."""
    pending_tail = 0.0
    dispatched: Dict[str, Tuple[float, int]] = {}
    for s in series:
        if s["name"] == "rtpu_scheduler_pending_tasks":
            _h, t = _head_tail(_num_points(s))
            pending_tail += t
        elif s["name"] == "rtpu_scheduler_tasks_dispatched_total":
            node = s["tags"].get("node", "?")
            pts = shape_points(s["points"], "delta")
            dispatched[node] = (sum(p[1] for p in pts), len(pts))
    if pending_tail < 1.0:
        return []
    out = []
    for node, (total, n) in sorted(dispatched.items()):
        if n >= 3 and total == 0.0:
            out.append({
                "metric": "rtpu_scheduler_tasks_dispatched_total",
                "tags": {"node": node}, "kind": "idle_node",
                "head": 0.0, "tail": 0.0, "ratio": 0.0,
                "window_s": window, "severity": "warn",
                "message": (f"node {node} dispatched no tasks over "
                            f"{window}s while ~{pending_tail:.0f} "
                            "task(s) sit queued — idle capacity or a "
                            "wedged dispatcher"),
            })
    return out
