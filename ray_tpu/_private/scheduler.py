"""Cluster-level scheduling policies.

Equivalent role to the reference's two-phase scheduler: a cluster-level node
selection (``ClusterResourceScheduler::GetBestSchedulableNode``,
``raylet/scheduling/cluster_resource_scheduler.h:44``) followed by local
dispatch. Policies mirrored: hybrid pack-then-spread with top-k
randomization (``policy/hybrid_scheduling_policy.cc:186``), spread,
node-affinity, placement-group bundles
(``policy/bundle_scheduling_policy.cc``).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .config import CONFIG
from .ids import NodeID, PlacementGroupID

ResourceDict = Dict[str, float]


# ------------------------------------------------------ scheduling strategies

@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin a task/actor to one node (reference:
    ``util/scheduling_strategies.py:41``)."""

    node_id: NodeID
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy:
    """Run inside a reserved placement-group bundle (reference:
    ``util/scheduling_strategies.py:135``)."""

    placement_group: "object"            # PlacementGroup handle or id
    placement_group_bundle_index: int = -1

    def pg_id(self) -> PlacementGroupID:
        pg = self.placement_group
        return pg if isinstance(pg, PlacementGroupID) else pg.id


SPREAD = "SPREAD"
DEFAULT = "DEFAULT"


# ------------------------------------------------------------ resource math

def fits(available: ResourceDict, demand: ResourceDict) -> bool:
    for k, v in demand.items():
        if v > 0 and available.get(k, 0.0) + 1e-9 < v:
            return False
    return True


def subtract(avail: ResourceDict, demand: ResourceDict) -> None:
    for k, v in demand.items():
        if v:
            avail[k] = avail.get(k, 0.0) - v


def add(avail: ResourceDict, demand: ResourceDict) -> None:
    for k, v in demand.items():
        if v:
            avail[k] = avail.get(k, 0.0) + v


def utilization(total: ResourceDict, available: ResourceDict) -> float:
    """Max over resources of used/total — the 'critical resource' view the
    hybrid policy scores with."""
    best = 0.0
    for k, cap in total.items():
        if cap <= 0:
            continue
        used = cap - available.get(k, 0.0)
        best = max(best, used / cap)
    return best


# ---------------------------------------------------------------- selection

def pick_node(
    demand: ResourceDict,
    strategy,
    candidates: List[Tuple[NodeID, ResourceDict, ResourceDict]],
    local_node: Optional[NodeID] = None,
    rng: Optional[random.Random] = None,
) -> Optional[NodeID]:
    """Choose a node for a task.

    ``candidates``: list of (node_id, total, available) for alive nodes.
    Returns None if no *feasible* node exists (demand exceeds every node's
    total capacity) — infeasible tasks wait in the queue like the
    reference's infeasible task set.
    """
    rng = rng or random
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        for nid, total, avail in candidates:
            if nid == strategy.node_id:
                if fits(total, demand):
                    return nid
                break
        if strategy.soft:
            return pick_node(demand, DEFAULT, candidates, local_node, rng)
        return None

    feasible = [(nid, total, avail) for nid, total, avail in candidates
                if fits(total, demand)]
    if not feasible:
        return None

    if strategy == SPREAD:
        # least-utilized first, ties broken randomly
        scored = sorted(feasible,
                        key=lambda c: (utilization(c[1], c[2]),
                                       rng.random()))
        for nid, total, avail in scored:
            if fits(avail, demand):
                return nid
        return scored[0][0]

    # hybrid DEFAULT: prefer packing onto nodes below the spread threshold
    # (lowest score wins), with top-k randomization to avoid herding.
    theta = CONFIG.scheduler_spread_threshold

    def score(c):
        nid, total, avail = c
        u = utilization(total, avail)
        if not fits(avail, demand):
            u += 100.0          # currently-full nodes only as a last resort
        if u <= theta:
            # below threshold: pack — prefer *higher* utilization, and the
            # local node as tiebreaker (reference: prefer local when legal)
            return (0, -u, 0 if nid == local_node else 1)
        return (1, u, 0 if nid == local_node else 1)

    ranked = sorted(feasible, key=score)
    k = max(1, int(len(ranked) * CONFIG.scheduler_top_k_fraction))
    return rng.choice(ranked[:k])[0]


# ------------------------------------------------------------ bundle packing

def pack_bundles(
    bundles: List[ResourceDict],
    strategy: str,
    candidates: List[Tuple[NodeID, ResourceDict, ResourceDict]],
) -> Optional[List[NodeID]]:
    """Assign placement-group bundles to nodes; None if unsatisfiable.

    Reference analogue: ``BundleSchedulingPolicy``
    (``policy/bundle_scheduling_policy.cc``) — PACK/SPREAD best-effort,
    STRICT_PACK single-node, STRICT_SPREAD distinct nodes.
    """
    avail = {nid: dict(a) for nid, _, a in candidates}
    order = [nid for nid, _, _ in candidates]

    if strategy == "STRICT_PACK":
        for nid in order:
            trial = dict(avail[nid])
            if all(_try_take(trial, b) for b in bundles):
                return [nid] * len(bundles)
        return None

    assignment: List[NodeID] = []
    if strategy == "STRICT_SPREAD":
        used_nodes = set()
        for b in bundles:
            placed = None
            for nid in order:
                if nid in used_nodes:
                    continue
                if _try_take(avail[nid], b):
                    placed = nid
                    break
            if placed is None:
                return None
            used_nodes.add(placed)
            assignment.append(placed)
        return assignment

    # PACK: fill nodes in order; SPREAD: round-robin over feasible nodes.
    spread = strategy == "SPREAD"
    idx = 0
    for b in bundles:
        placed = None
        tries = list(range(len(order)))
        if spread:
            tries = tries[idx:] + tries[:idx]
        for i in tries:
            nid = order[i]
            if _try_take(avail[nid], b):
                placed = nid
                idx = (i + 1) % len(order)
                break
        if placed is None:
            return None
        assignment.append(placed)
    return assignment


def _try_take(avail: ResourceDict, demand: ResourceDict) -> bool:
    if fits(avail, demand):
        subtract(avail, demand)
        return True
    return False
