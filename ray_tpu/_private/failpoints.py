"""Deterministic fault injection for the chaos harness.

Reference analogue: the C++ core's testing fault-injection flags
(``RAY_testing_asio_delay_us`` and the chaos node-killer of
``_private/test_utils.py``) — but *site-addressed*: a failpoint fires at
a **named protocol point** (a ring hop, a hierarchical phase boundary,
an actor-call entry), so a chaos test kills a rank at an exact position
inside a schedule instead of racing a sleep against the wall clock.

Activation is either the ``RTPU_FAILPOINTS`` environment variable
(parsed at import — covers whole node processes and the workers they
spawn) or :func:`activate` at runtime (a test arms one specific actor
process through an actor method).

Spec grammar (entries joined by ``;``)::

    entry  := site "=" action ["@" guard {"&" guard}] ["!once"]
    action := "kill" | "exit" | "raise" | "sleep:<seconds>"
    guard  := key "=" value     # string-compared against fp() ctx

Examples::

    coll.op.begin=kill@seq=2            # SIGKILL self entering seq-2 op
    coll.hier.phase=kill@phase=up&chunk=1!once
    actor.call.begin=sleep:0.5@method=train_step

Actions: ``kill`` SIGKILLs the current process (the chaos default — the
runtime must recover from an instantaneous death, not a clean exit);
``exit`` is ``os._exit(1)``; ``raise`` raises :class:`FailpointError`;
``sleep:<s>`` delays the site (straggler injection). ``!once`` disarms
the entry after its first firing.

Every ``fp(<site>)`` call site must name a site registered in
``_SITES`` — linted both directions by ``scripts/check_concurrency.py``
(rule g), exactly like config knobs: an unregistered site string is a
typo waiting to never fire.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Dict, List, Optional

# Registered sites: the only strings fp() may be called with. Keep the
# comment naming where each is planted — the lint enforces >= 1 caller.
_SITES = (
    "coll.op.begin",        # collective.py _run_op: one public op starts
    "coll.ring.rs_hop",     # collective.py ring reduce-scatter: per hop
    "coll.hier.phase",      # collective.py hierarchical allreduce phases
    "coll.reform.join",     # collective.py: entering a reform round
    "actor.call.begin",     # worker.py: an actor method is about to run
    "worker.task.begin",    # worker.py: a plain task is about to run
)


class FailpointError(RuntimeError):
    """Raised by the ``raise`` action."""


class _Entry:
    __slots__ = ("site", "action", "arg", "guards", "once", "spent")

    def __init__(self, site: str, action: str, arg: Optional[float],
                 guards: Dict[str, str], once: bool):
        self.site = site
        self.action = action
        self.arg = arg
        self.guards = guards
        self.once = once
        self.spent = False

    def matches(self, ctx: Dict[str, Any]) -> bool:
        if self.spent:
            return False
        for k, v in self.guards.items():
            if str(ctx.get(k)) != v:
                return False
        return True


# Module state: written only by activate()/deactivate(); fp() reads a
# local snapshot, so no lock is needed (an entry list swap is atomic).
_entries: List[_Entry] = []


def parse(spec: str) -> List[_Entry]:
    """Parse one spec string; raises ValueError on malformed entries or
    unregistered site names (a typo must fail loudly at arm time, not
    silently never fire)."""
    out: List[_Entry] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        once = raw.endswith("!once")
        if once:
            raw = raw[:-len("!once")]
        if "=" not in raw:
            raise ValueError(f"failpoint entry {raw!r}: expected "
                             "site=action[@k=v&...][!once]")
        site, rest = raw.split("=", 1)
        site = site.strip()
        if site not in _SITES:
            raise ValueError(
                f"failpoint site {site!r} is not registered in "
                f"failpoints._SITES {sorted(_SITES)}")
        action_part, _, guard_part = rest.partition("@")
        action, _, argstr = action_part.strip().partition(":")
        if action not in ("kill", "exit", "raise", "sleep"):
            raise ValueError(f"failpoint action {action!r}: expected "
                             "kill | exit | raise | sleep:<seconds>")
        arg = None
        if action == "sleep":
            try:
                arg = float(argstr or "0.1")
            except ValueError:
                raise ValueError(
                    f"failpoint sleep arg {argstr!r} is not a number"
                ) from None
        guards: Dict[str, str] = {}
        if guard_part:
            for g in guard_part.split("&"):
                if "=" not in g:
                    raise ValueError(
                        f"failpoint guard {g!r}: expected key=value")
                k, v = g.split("=", 1)
                guards[k.strip()] = v.strip()
        out.append(_Entry(site, action, arg, guards, once))
    return out


def activate(spec: str) -> int:
    """Arm failpoints in THIS process from a spec string; returns the
    number of armed entries. Replaces any previously-armed set."""
    global _entries
    _entries = parse(spec)
    return len(_entries)


def deactivate() -> None:
    global _entries
    _entries = []


def active() -> bool:
    return bool(_entries)


def fp(site: str, **ctx: Any) -> None:
    """One named protocol point. No-op (one list check) unless an armed
    entry's site and guards match the call context."""
    entries = _entries
    if not entries:
        return
    for ent in entries:
        if ent.site != site or not ent.matches(ctx):
            continue
        if ent.once:
            ent.spent = True
        if ent.action == "kill":
            # an instantaneous death, exactly like the OOM killer / a
            # crashed host: no atexit, no socket FIN from our side
            os.kill(os.getpid(), signal.SIGKILL)
        elif ent.action == "exit":
            os._exit(1)
        elif ent.action == "raise":
            raise FailpointError(f"failpoint {site} fired (ctx={ctx})")
        elif ent.action == "sleep":
            time.sleep(ent.arg or 0.0)


_env_spec = os.environ.get("RTPU_FAILPOINTS")
if _env_spec:
    activate(_env_spec)
