"""Node memory monitor and OOM worker-killing policy.

When system memory crosses a usage threshold the node kills a running
worker — preferring retriable work, newest first — instead of letting the
kernel OOM-killer take down the raylet or an arbitrary process.

Reference analogues: ``src/ray/common/memory_monitor.h:52`` (cgroup/proc
usage polling + threshold callback) and
``src/ray/raylet/worker_killing_policy.h:34`` (retriable-LIFO victim
selection). The detection here is the same /proc + cgroup-v2 reading the
reference does; the policy is the same retriable-first LIFO.

Tests (and single-host simulations) can force the reading with the
``RTPU_TEST_MEMORY_USAGE_FRACTION`` environment variable, which is
re-read on every probe so pressure can be raised and dropped mid-run.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional, Tuple

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# cgroup v2 (container) limits take precedence over host totals: inside a
# container /proc/meminfo shows the HOST, and the kernel kills at the
# cgroup limit long before the host is full.
_CGROUP_CURRENT = "/sys/fs/cgroup/memory.current"
_CGROUP_MAX = "/sys/fs/cgroup/memory.max"


def _read_int_file(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    if raw == "max":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _proc_meminfo() -> Tuple[Optional[int], Optional[int]]:
    """(total_bytes, available_bytes) from /proc/meminfo."""
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total is not None and avail is not None:
                    break
    except OSError:
        pass
    return total, avail


def process_rss_bytes(pid: int) -> int:
    """Resident set size of one process (0 if gone)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        return 0


class MemoryMonitor:
    """Polls system/cgroup memory; reports the used fraction."""

    def usage_fraction(self) -> float:
        forced = os.environ.get("RTPU_TEST_MEMORY_USAGE_FRACTION")
        if forced:
            try:
                return float(forced)
            except ValueError:
                pass
        cur, limit = (_read_int_file(_CGROUP_CURRENT),
                      _read_int_file(_CGROUP_MAX))
        if cur is not None and limit:
            return cur / limit
        total, avail = _proc_meminfo()
        if total and avail is not None:
            return 1.0 - avail / total
        return 0.0

    def snapshot(self) -> dict:
        """Usage fraction plus totals from the SAME source the fraction
        came from — inside a container the cgroup limit is the relevant
        total, not the host's /proc/meminfo."""
        frac = self.usage_fraction()
        limit = _read_int_file(_CGROUP_MAX)
        if (_read_int_file(_CGROUP_CURRENT) is not None and limit
                and not os.environ.get("RTPU_TEST_MEMORY_USAGE_FRACTION")):
            total = limit
        else:
            total = _proc_meminfo()[0] or 0
        return {
            "usage_fraction": round(frac, 4),
            "total_bytes": total,
            "available_bytes": max(0, int(total * (1.0 - frac))),
        }


def pick_oom_victim(workers: Iterable,
                    actor_restartable=lambda actor_id: False,
                    rss_of=lambda worker: 0,
                    ) -> Optional[object]:
    """Choose the worker to kill under memory pressure.

    Policy (reference ``worker_killing_policy.h:34`` RetriableLIFO):
    prefer workers whose in-flight work can be retried/restarted
    (retriable tasks first, then restartable actors); among equals kill
    the largest resident set (``rss_of``, the kill that actually
    relieves the pressure), and only then the most recently started —
    the oldest work has the most sunk cost. ``rss_of`` defaults to a
    constant so callers without pid access keep pure retriable-LIFO.
    Idle/starting workers are not considered (they hold no task to
    shed; idle eviction handles them separately).
    """
    best = None
    best_key = None
    for w in workers:
        if w.task is None and w.actor_id is None:
            continue
        if w.state not in ("BUSY", "ACTOR"):
            continue
        if w.actor_id is not None:
            # rank actors below plain tasks at equal retriability: an
            # actor restart loses its whole state, a task retry only
            # its own progress
            retriable = 1 if actor_restartable(w.actor_id) else 0
        else:
            rec = w.task
            retriable = 2 if (rec.retries_left > 0
                              or getattr(rec, "oom_retries_left", 0) > 0
                              ) else 0
        # newest *assignment* as the last tiebreak (pooled workers are
        # reused, so process start time would misrank sunk cost); fall
        # back to process start for workers predating assignment stamps
        key = (retriable, rss_of(w),
               getattr(w, "assigned_at", 0.0) or w.started_at)
        if best_key is None or key > best_key:
            best, best_key = w, key
    return best
