"""Process-global runtime context (driver or worker)."""

from __future__ import annotations

import contextvars
from typing import Any, Optional

# The connected CoreClient for this process (driver after init(), worker
# after registration). Reference analogue: ray._private.worker.global_worker.
current_client: Optional[Any] = None

# Set inside a worker process while executing a task.
current_task_id = None
# Display name of the running task (spec.name): read by the sampling
# profiler's task_filter — best-effort under max_concurrency>1.
current_task_name = None
current_actor_id = None
current_accel_ids = None        # TPU slot indices assigned at dispatch
in_worker: bool = False

# Set by the worker runtime once it hosts an actor instance: the
# callable behind ray_tpu.actor_checkpoint() (captures + persists the
# actor's state now; see WorkerRuntime.checkpoint_now).
actor_checkpoint_hook = None

# Per-task namespace: a ContextVar so concurrent method calls of a
# threaded/async actor each see their own submitter's namespace.
current_namespace: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_namespace", default=None)


def active_namespace() -> str:
    ns = current_namespace.get()
    if ns is not None:
        return ns
    return current_client.namespace if current_client else "default"


def require_client():
    if current_client is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first")
    return current_client
