"""Process-global runtime context (driver or worker)."""

from __future__ import annotations

import contextvars
from typing import Any, Optional

# The connected CoreClient for this process (driver after init(), worker
# after registration). Reference analogue: ray._private.worker.global_worker.
current_client: Optional[Any] = None

# Set inside a worker process while executing a task.
current_task_id = None
# Display name of the running task (spec.name): read by the sampling
# profiler's task_filter — best-effort under max_concurrency>1.
current_task_name = None
current_actor_id = None
current_accel_ids = None        # TPU slot indices assigned at dispatch
in_worker: bool = False

# Set by the worker runtime once it hosts an actor instance: the
# callable behind ray_tpu.actor_checkpoint() (captures + persists the
# actor's state now; see WorkerRuntime.checkpoint_now).
actor_checkpoint_hook = None

# Per-task namespace: a ContextVar so concurrent method calls of a
# threaded/async actor each see their own submitter's namespace.
current_namespace: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_namespace", default=None)

# Request-scoped baggage riding the task spec (reference analogue: W3C
# trace baggage / Serve's request context): a submitter binds a compact
# tuple here and the next submissions carry it in spec.request_ctx —
# INSIDE the one spec pickle stream, not as an extra arg slot (an arg
# slot costs a separate pickle + load per call; the request_ab overhead
# gate prices this path). Workers re-bind it around task execution, so
# the whole nested call tree of one serve request shares the baggage.
request_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_request_ctx", default=None)

# Monotonic receive stamp of the actor call carrying request baggage
# (set by the worker beside request_ctx, only for requests): the
# replica's skew-free fallback for queue-wait when cross-node wall
# clocks disagree (enqueued_at comes from the HANDLE's clock).
request_recv_t: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_request_recv_t", default=None)


def active_namespace() -> str:
    ns = current_namespace.get()
    if ns is not None:
        return ns
    return current_client.namespace if current_client else "default"


def require_client():
    if current_client is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first")
    return current_client
