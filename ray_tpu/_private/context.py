"""Process-global runtime context (driver or worker)."""

from __future__ import annotations

from typing import Any, Optional

# The connected CoreClient for this process (driver after init(), worker
# after registration). Reference analogue: ray._private.worker.global_worker.
current_client: Optional[Any] = None

# Set inside a worker process while executing a task.
current_task_id = None
current_actor_id = None
in_worker: bool = False


def require_client():
    if current_client is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first")
    return current_client
