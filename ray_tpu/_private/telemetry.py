"""In-process runtime telemetry core: the record path is a sharded-lock
dict update — never an RPC.

Equivalent role to the reference's per-node ``MetricsAgent`` →
Prometheus pipeline (``_private/metrics_agent.py``): every process
records into process-local shards; a background flusher batch-pushes
*deltas* to the control plane (direct plane call in node processes, one
fire-and-forget ``PROFILE_EVENT`` frame in workers/drivers), where they
merge into the cluster-wide table served by ``export_prometheus()``,
the dashboard ``/api/metrics`` endpoint and
``state.api.summarize_metrics()``.

Three layers:

1. record  — ``counter_inc`` / ``gauge_set`` / ``hist_observe``:
   lock-cheap shard update, histogram stored as cumulative bucket
   counts + sum/count (bounded memory, unlike raw-observation lists).
2. flush   — ``flush()`` collects per-shard deltas since the last
   flush and ships one batch; runs on a timer, after each worker task,
   and synchronously before an export.
3. sample  — a per-node sampler thread records host stats (RSS, load,
   object-store fill) and JAX device stats (``device.memory_stats()``
   HBM use/limit, jit compile counts), degrading to a no-op on
   CPU-only JAX.

When tracing is enabled, histogram observations carry the current
``trace_id`` as an exemplar so slow outliers link back to spans.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import warnings
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import fieldsan
from . import locksan
from .config import CONFIG

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0)

_N_SHARDS = 8

# ------------------------------------------------------- quantile digest
# Fixed-memory streaming quantile sketch (small merging t-digest): a
# sorted list of (mean, weight) centroids capped at _DIGEST_CENTROIDS,
# with raw observations staged in a short buffer and folded in by a
# single merge pass whose per-centroid weight limit follows the t-digest
# k1 scale (4·total·q·(1-q)/K) — tails stay near-singleton, the middle
# coarsens, so p50/p95/p99 stay accurate without retaining samples.
# Digests ship through the same delta flusher as histograms: the record
# path keeps a cumulative digest (local snapshots) AND a since-last-
# flush digest (the shipped delta); the control plane merges deltas by
# centroid concatenation + the same compress pass, which is exactly the
# t-digest merge operation — so per-process sketches combine into one
# cluster-wide per-series quantile view.

_DIGEST_CENTROIDS = 64
_DIGEST_BUF = 32


def _digest_merge_pass(items: List[list], k: int) -> List[list]:
    """One merging pass over sorted (mean, weight) pairs: cluster
    weights bounded by the t-digest k1 scale 4·total·q·(1-q)/k, so the
    middle coarsens while the tails stay near-singleton."""
    total = sum(c[1] for c in items)
    out: List[list] = []
    cum = 0.0
    cur_mean, cur_w = items[0]
    for mean, w in items[1:]:
        q = (cum + cur_w / 2.0) / total
        limit = max(1.0, 4.0 * total * q * (1.0 - q) / k)
        if cur_w + w <= limit:
            cur_mean += (mean - cur_mean) * (w / (cur_w + w))
            cur_w += w
        else:
            out.append([cur_mean, cur_w])
            cum += cur_w
            cur_mean, cur_w = mean, w
    out.append([cur_mean, cur_w])
    return out


def _digest_compress(items: List[list], k: int) -> List[list]:
    """Compress (mean, weight) pairs to at most ~2k centroids. The k1
    pass alone converges to O(k·ln n) clusters (the weight limit keeps
    shrinking toward the tails), so re-run it with a halved k until the
    hard cap holds — memory stays FIXED regardless of stream length."""
    if not items:
        return []
    items.sort(key=lambda c: c[0])
    out = _digest_merge_pass(items, k)
    kk = k
    while len(out) > 2 * k and kk > 1:
        kk //= 2
        out = _digest_merge_pass(out, kk)
    return out


class _Digest:
    """One digest series: compressed centroids + a small staging buffer
    (bounded memory; no sample retention beyond the buffer)."""

    __slots__ = ("cents", "buf", "count", "sum", "min", "max")

    def __init__(self):
        self.cents: List[list] = []
        self.buf: List[float] = []
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, value: float) -> None:
        self.buf.append(value)
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if len(self.buf) >= _DIGEST_BUF:
            self._fold()

    def add_many(self, values: List[float], lazy: bool = False) -> None:
        """Bulk fold: ONE compress pass for the whole batch (the
        record path stages raw values and drains them here at flush
        cadence — per-observation cost stays an append). ``lazy``
        defers even that compress by parking the batch in the staging
        buffer until it hits ~_DIGEST_STAGE — the cumulative digest
        (read only by local snapshots) folds on a much coarser cadence
        than the per-flush delta, halving the flush-path cost."""
        if not values:
            return
        self.count += len(values)
        self.sum += sum(values)
        mn, mx = min(values), max(values)
        if mn < self.min:
            self.min = mn
        if mx > self.max:
            self.max = mx
        if lazy:
            self.buf.extend(values)
            if len(self.buf) >= _DIGEST_STAGE:
                self._fold()
            return
        self.cents = _digest_compress(
            self.cents + [[v, 1.0] for v in self.buf]
            + [[v, 1.0] for v in values], _DIGEST_CENTROIDS)
        self.buf = []

    def _fold(self) -> None:
        if self.buf:
            self.cents = _digest_compress(
                self.cents + [[v, 1.0] for v in self.buf],
                _DIGEST_CENTROIDS)
            self.buf = []

    def merge_payload(self, payload: dict) -> None:
        if not payload or not payload.get("count"):
            return
        self._fold()
        self.cents = _digest_compress(
            self.cents + [list(c) for c in payload.get("centroids") or ()],
            _DIGEST_CENTROIDS)
        self.count += int(payload["count"])
        self.sum += float(payload.get("sum", 0.0))
        self.min = min(self.min, float(payload.get("min", self.min)))
        self.max = max(self.max, float(payload.get("max", self.max)))

    def to_payload(self) -> dict:
        self._fold()
        return {"centroids": [list(c) for c in self.cents],
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max}


def compress_centroids(items: List[list], k: int) -> List[list]:
    """Public fold for OTHER holders of digest centroid lists (the
    metrics-history rings recompress frame payloads to a coarser cap):
    same k1 merge pass the live digests use."""
    return _digest_compress(items, k)


def merge_digest_payloads(cur: Optional[dict], new: dict) -> dict:
    """Merge two shipped digest payloads (the control-plane fold)."""
    if not cur or not cur.get("count"):
        return {"centroids": [list(c) for c in new.get("centroids") or ()],
                "count": int(new.get("count", 0)),
                "sum": float(new.get("sum", 0.0)),
                "min": float(new.get("min", float("inf"))),
                "max": float(new.get("max", float("-inf")))}
    if not new.get("count"):
        return cur
    d = _Digest()
    d.merge_payload(cur)
    d.merge_payload(new)
    return d.to_payload()


def digest_quantile(payload: Optional[dict], q: float) -> float:
    """Estimate quantile ``q`` (0..1) from a shipped digest payload
    (midpoint interpolation between centroid means, clamped to the
    exact observed min/max)."""
    if not payload or not payload.get("count"):
        return 0.0
    cents = sorted((list(c) for c in payload.get("centroids") or ()),
                   key=lambda c: c[0])
    lo = float(payload.get("min", cents[0][0] if cents else 0.0))
    hi = float(payload.get("max", cents[-1][0] if cents else 0.0))
    if not cents:
        return lo
    total = sum(c[1] for c in cents)
    target = q * total
    cum = 0.0
    prev_mean, prev_mid = lo, 0.0
    for mean, w in cents:
        mid = cum + w / 2.0
        if target <= mid:
            if mid == prev_mid:
                return max(lo, min(hi, mean))
            frac = (target - prev_mid) / (mid - prev_mid)
            return max(lo, min(hi, prev_mean + (mean - prev_mean) * frac))
        prev_mean, prev_mid = mean, mid
        cum += w
    return hi


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count", "exemplar",
                 "f_counts", "f_sum", "f_count")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)      # last slot = +Inf
        self.sum = 0.0
        self.count = 0
        self.exemplar: Optional[dict] = None
        self.f_counts = [0] * (len(buckets) + 1)    # flushed watermark
        self.f_sum = 0.0
        self.f_count = 0


@fieldsan.guarded
class _Shard:
    def __init__(self):
        self.lock = locksan.lock("telemetry.shard")
        self.counters: Dict[tuple, list] = {}       # key -> [live, flushed]
        self.gauges: Dict[tuple, tuple] = {}        # key -> (value, ts)
        self.gauges_dirty: set = set()              # keys set since flush
        self.hists: Dict[tuple, _Hist] = {}
        # key -> [cumulative _Digest, since-last-flush _Digest, raw
        # staging buffer]. The record path ONLY appends to the staging
        # buffer; values drain into both digests (one bulk compress
        # each) at flush/snapshot time, or when the buffer hits
        # _DIGEST_STAGE cap under a burst — so the per-observation cost
        # is a list append, like counters.
        self.digests: Dict[tuple, list] = {}


_shards = [_Shard() for _ in range(_N_SHARDS)]

# metric metadata, keyed by NAME (Prometheus requires one kind and one
# bucket layout per name); conflicting re-definitions warn and keep the
# first definition instead of silently clobbering buckets
_meta: Dict[str, dict] = {}
_meta_lock = locksan.lock("telemetry.meta")
_conflict_warned: set = set()

# per-process node registry: NodeService instances sampled by the
# sampler thread and used as the preferred flush transport (direct
# plane call — no socket hop for node/head processes)
_nodes: List[Any] = []
_runtime_lock = locksan.lock("telemetry.runtime")
_flusher_started = False
_sampler_started = False
_last_flush = 0.0
_jax_listener_installed = False


def _shard(key: tuple) -> _Shard:
    return _shards[hash(key) & (_N_SHARDS - 1)]


# bumped by reset(): pinned digest_series handles re-resolve into the
# fresh shard tables instead of writing to orphaned entries
_digest_gen = 0


def define(kind: str, name: str, description: str = "",
           buckets: Optional[Sequence[float]] = None) -> str:
    """Register metric metadata once; returns ``name`` so module-level
    constants read naturally (no side effects beyond the registry —
    importing an instrumented module must not spawn threads). Kind/
    bucket conflicts warn and keep the first definition."""
    b = tuple(buckets) if buckets else (tuple(DEFAULT_BUCKETS)
                                        if kind == "histogram" else None)
    with _meta_lock:
        existing = _meta.get(name)
        if existing is None:
            _meta[name] = {"kind": kind, "description": description,
                           "buckets": b}
        elif (existing["kind"] != kind
              or (kind == "histogram" and existing["buckets"] != b)):
            if name not in _conflict_warned:
                _conflict_warned.add(name)
                warnings.warn(
                    f"metric {name!r} re-defined with conflicting "
                    f"kind/buckets ({existing['kind']}/"
                    f"{existing['buckets']} vs {kind}/{b}); keeping the "
                    "first definition", stacklevel=2)
        elif description and not existing["description"]:
            existing["description"] = description
    return name


def enabled() -> bool:
    return bool(CONFIG.telemetry_enabled)


# ------------------------------------------------------------ record path

def counter_inc(name: str, value: float = 1.0, tags: tuple = ()) -> None:
    if not CONFIG.telemetry_enabled:
        return
    if not _flusher_started:
        _ensure_flusher()
    key = (name, tags)
    sh = _shard(key)
    with sh.lock:
        ent = sh.counters.get(key)
        if ent is None:
            sh.counters[key] = [value, 0.0]
        else:
            ent[0] += value


def gauge_set(name: str, value: float, tags: tuple = ()) -> None:
    if not CONFIG.telemetry_enabled:
        return
    if not _flusher_started:
        _ensure_flusher()
    key = (name, tags)
    sh = _shard(key)
    with sh.lock:
        sh.gauges[key] = (value, time.time())
        sh.gauges_dirty.add(key)


def gauge_delete(name: str, tags: tuple = ()) -> None:
    """Retire one gauge SERIES cluster-wide: ships a NaN marker through
    the normal delta flush; the control plane (and the local snapshot)
    drop the series instead of exporting the marker. For series whose
    identity dies with its subject — a stopped serve replica's queue
    depth must not read as a live value (or a sentinel) forever on
    Prometheus/dashboard/summary surfaces. Best-effort under races: a
    straggling publish from the dying process can resurrect the series
    until its next delete."""
    gauge_set(name, float("nan"), tags)


def hist_observe(name: str, value: float, tags: tuple = (),
                 boundaries: Optional[Tuple[float, ...]] = None) -> None:
    if not CONFIG.telemetry_enabled:
        return
    if not _flusher_started:
        _ensure_flusher()
    if boundaries is None:
        m = _meta.get(name)
        boundaries = (m["buckets"] if m and m.get("buckets")
                      else DEFAULT_BUCKETS)
    key = (name, tags)
    sh = _shard(key)
    exemplar = None
    if CONFIG.tracing_enabled:
        from ..util import tracing
        ctx = tracing.get_current_context()
        if ctx and ctx.get("trace_id"):
            exemplar = {"trace_id": ctx["trace_id"], "value": value,
                        "ts": time.time()}
    idx = bisect_left(boundaries, value)
    with sh.lock:
        h = sh.hists.get(key)
        if h is None:
            h = sh.hists[key] = _Hist(tuple(boundaries))
        h.counts[min(idx, len(h.counts) - 1)] += 1
        h.sum += value
        h.count += 1
        if exemplar is not None:
            h.exemplar = exemplar


_DIGEST_STAGE = 512


def _drain_digest(ent: list) -> None:
    """Fold a series' staged raw values into both its cumulative and
    its since-last-flush digest (caller holds the shard lock). The
    cumulative side folds LAZILY — it is only read by local snapshots,
    so the per-flush compress cost is one pass (the shipped delta),
    not two."""
    if ent[2]:
        ent[0].add_many(ent[2], lazy=True)
        ent[1].add_many(ent[2])
        ent[2] = []


def digest_observe(name: str, value: float, tags: tuple = ()) -> None:
    """Record one observation into a streaming quantile digest (fixed
    memory, same sharded no-RPC record path as histograms; the delta
    flusher ships centroids and the plane t-digest-merges them). The
    record path is a list append — compression runs at flush cadence
    (or at the staging cap under a burst), never per observation."""
    if not CONFIG.telemetry_enabled:
        return
    if not _flusher_started:
        _ensure_flusher()
    _digest_record((name, tags), float(value))


def digest_series(name: str, tags: tuple = ()):
    """Prebind one digest series for per-call-site hot paths (serve
    replicas record two digests per request): returns a mutable handle
    for ``digest_record`` that caches the resolved shard + entry, so
    the per-observation cost is one lock + one list append — no key
    hash, no dict lookup. Handles survive ``reset()`` via a generation
    check (the next record re-resolves into the fresh shard tables)."""
    return [(name, tags), None, None, -1]


def digest_record(series, value: float) -> None:
    """Record into a ``digest_series`` handle (hot-path variant of
    ``digest_observe`` — same semantics, fewer per-observation costs)."""
    # direct _values read: __getattr__ dispatch costs ~0.4µs/read and
    # this runs twice per serve request
    if not CONFIG._values["telemetry_enabled"]:
        return
    if not _flusher_started:
        _ensure_flusher()
    sh = series[1]
    if series[3] != _digest_gen:
        key = series[0]
        sh = _shard(key)
        with sh.lock:
            ent = sh.digests.get(key)
            if ent is None:
                ent = [_Digest(), _Digest(), []]
                sh.digests[key] = ent
        series[1], series[2], series[3] = sh, ent, _digest_gen
    ent = series[2]
    with sh.lock:
        ent[2].append(float(value))
        if len(ent[2]) >= _DIGEST_STAGE:
            _drain_digest(ent)


def _digest_record(key: tuple, value: float) -> None:
    sh = _shard(key)
    with sh.lock:
        ent = sh.digests.get(key)
        if ent is None:
            ent = sh.digests[key] = [_Digest(), _Digest(), []]
        ent[2].append(value)
        if len(ent[2]) >= _DIGEST_STAGE:
            _drain_digest(ent)


# --------------------------------------------------------------- flushing

_last_digest_ship = 0.0
_DIGEST_SHIP_INTERVAL_S = 1.0


def _collect_deltas() -> Optional[dict]:
    """Per-shard deltas since the last collect; None when nothing moved.
    Advances the flushed watermark, so call only with a transport in
    hand. Digest deltas ship on their own coarser cadence (~1s):
    counters/gauges are cheap to ship per flush, but a digest delta
    costs a compress pass here AND a merge pass on the plane — at the
    0.2s task-boundary flush rate that CPU competes with the serving
    path itself on small boxes, for freshness nothing consumes."""
    global _last_digest_ship
    counters: Dict[tuple, float] = {}
    gauges: Dict[tuple, tuple] = {}
    hists: Dict[tuple, dict] = {}
    digests: Dict[tuple, dict] = {}
    now = time.monotonic()
    ship_digests = now - _last_digest_ship >= _DIGEST_SHIP_INTERVAL_S
    for sh in _shards:
        with sh.lock:
            for key, ent in sh.counters.items():
                d = ent[0] - ent[1]
                if d:
                    counters[key] = d
                    ent[1] = ent[0]
            for key in sh.gauges_dirty:
                if key in sh.gauges:
                    gauges[key] = sh.gauges[key]
                    if sh.gauges[key][0] != sh.gauges[key][0]:
                        # NaN delete marker: ship it once, then drop
                        # the local series too
                        del sh.gauges[key]
            sh.gauges_dirty.clear()
            for key, h in sh.hists.items():
                dc = [a - b for a, b in zip(h.counts, h.f_counts)]
                if h.count - h.f_count or h.exemplar is not None:
                    hists[key] = {"buckets": h.buckets, "counts": dc,
                                  "sum": h.sum - h.f_sum,
                                  "count": h.count - h.f_count,
                                  "exemplar": h.exemplar}
                    h.f_counts = list(h.counts)
                    h.f_sum = h.sum
                    h.f_count = h.count
                    h.exemplar = None
            if ship_digests:
                for key, dent in sh.digests.items():
                    _drain_digest(dent)
                    if dent[1].count:
                        digests[key] = dent[1].to_payload()
                        dent[1] = _Digest()
    if digests:
        _last_digest_ship = now
    if not (counters or gauges or hists or digests):
        return None
    with _meta_lock:
        meta = {name: dict(m) for name, m in _meta.items()}
    return {"counters": counters, "gauges": gauges, "hists": hists,
            "digests": digests, "meta": meta}


def _transport():
    """Preferred delta sink: a registered node's control plane (direct,
    no socket), else this process's connected client (one
    fire-and-forget PROFILE_EVENT frame)."""
    with _runtime_lock:
        nodes = list(_nodes)
    for node in nodes:
        if not getattr(node, "dead", False):
            return lambda payload, _g=node.gcs: _g.record_metrics(payload)
    from . import context as _ctx
    client = _ctx.current_client
    if client is not None and not client._closed.is_set():
        return lambda payload, _c=client: _c.send_profile_event(
            "metrics", payload)
    return None


def _restore_deltas(payload: dict) -> None:
    """A send failed after the watermark advanced: roll the watermark
    back so the deltas ship with the next flush instead of vanishing."""
    for key, d in payload.get("counters", {}).items():
        sh = _shard(key)
        with sh.lock:
            ent = sh.counters.get(key)
            if ent is not None:
                ent[1] -= d
    for key, vt in payload.get("gauges", {}).items():
        sh = _shard(key)
        with sh.lock:
            if key in sh.gauges:
                sh.gauges_dirty.add(key)
            elif vt[0] != vt[0]:
                # a NaN delete marker was dropped at collect time; the
                # failed send must re-queue it or the plane never
                # forgets the series
                sh.gauges[key] = tuple(vt)
                sh.gauges_dirty.add(key)
    for key, hd in payload.get("hists", {}).items():
        sh = _shard(key)
        with sh.lock:
            h = sh.hists.get(key)
            if h is None or h.buckets != tuple(hd["buckets"]):
                continue
            h.f_counts = [a - b for a, b in zip(h.f_counts, hd["counts"])]
            h.f_sum -= hd["sum"]
            h.f_count -= hd["count"]
            if h.exemplar is None:
                h.exemplar = hd.get("exemplar")
    for key, dd in payload.get("digests", {}).items():
        sh = _shard(key)
        with sh.lock:
            ent = sh.digests.get(key)
            if ent is None:
                ent = sh.digests[key] = [_Digest(), _Digest(), []]
                ent[0].merge_payload(dd)
            ent[1].merge_payload(dd)


def flush() -> None:
    """Ship accumulated deltas to the control plane. Never raises; with
    no transport available (or a failed send) the deltas keep
    accumulating locally for the next attempt."""
    global _last_flush
    sink = _transport()
    if sink is None:
        return
    payload = _collect_deltas()
    if payload is None:
        return
    _last_flush = time.monotonic()
    try:
        sink(payload)
    except Exception:   # noqa: BLE001 — telemetry must never break work
        _restore_deltas(payload)


def maybe_flush(min_interval_s: float = 0.2) -> None:
    """Rate-limited flush for per-task-completion call sites: frequent
    enough for freshness, bounded so a storm of tiny tasks doesn't pay
    one control-plane frame each."""
    if time.monotonic() - _last_flush >= min_interval_s:
        flush()


def _ensure_flusher() -> None:
    global _flusher_started
    if _flusher_started:
        return
    with _runtime_lock:
        if _flusher_started:
            return
        _flusher_started = True
    t = threading.Thread(target=_flush_loop, daemon=True,
                         name="rtpu-telemetry-flush")
    t.start()


def _flush_loop() -> None:
    while True:
        time.sleep(max(CONFIG.metrics_report_interval_ms, 250) / 1000.0)
        _install_jax_compile_listener()
        try:
            flush()
        except Exception:   # noqa: BLE001
            pass


# ------------------------------------------------------------- snapshots

def snapshot_local() -> dict:
    """Merged totals of this process's shards (fallback export surface
    when no runtime is connected; also what unit tests inspect)."""
    counters: Dict[tuple, float] = {}
    gauges: Dict[tuple, tuple] = {}
    hists: Dict[tuple, dict] = {}
    digests: Dict[tuple, dict] = {}
    for sh in _shards:
        with sh.lock:
            for key, ent in sh.counters.items():
                counters[key] = counters.get(key, 0.0) + ent[0]
            gauges.update((k, v) for k, v in sh.gauges.items()
                          if v[0] == v[0])    # skip NaN delete markers
            for key, h in sh.hists.items():
                hists[key] = {"buckets": h.buckets,
                              "counts": list(h.counts),
                              "sum": h.sum, "count": h.count,
                              "exemplar": h.exemplar}
            for key, dent in sh.digests.items():
                _drain_digest(dent)
                digests[key] = dent[0].to_payload()
    with _meta_lock:
        meta = {name: dict(m) for name, m in _meta.items()}
    return {"counters": counters, "gauges": gauges, "hists": hists,
            "digests": digests, "meta": meta}


def reset() -> None:
    """Drop all local series and node registrations (session teardown:
    the next init() must not inherit this session's samples)."""
    global _digest_gen
    _digest_gen += 1
    for sh in _shards:
        with sh.lock:
            sh.counters.clear()
            sh.gauges.clear()
            sh.gauges_dirty.clear()
            sh.hists.clear()
            sh.digests.clear()
    with _runtime_lock:
        _nodes.clear()


# ----------------------------------------------------- node runtime hooks

M_TASKS_SUBMITTED = define(
    "counter", "rtpu_scheduler_tasks_submitted_total",
    "Tasks submitted to this node's scheduler (incl. actor calls)")
M_TASKS_DISPATCHED = define(
    "counter", "rtpu_scheduler_tasks_dispatched_total",
    "Tasks assigned to a worker by the local dispatcher")
M_TASKS_FINISHED = define(
    "counter", "rtpu_scheduler_tasks_finished_total",
    "Tasks completed on this node, tagged status=ok|error")
M_QUEUE_WAIT = define(
    "histogram", "rtpu_scheduler_queue_wait_seconds",
    "Pending-queue wait between task arrival and worker assignment")
M_PENDING_TASKS = define(
    "gauge", "rtpu_scheduler_pending_tasks",
    "Tasks in the local ready-to-dispatch queue")
M_LEASE_REUSED = define(
    "counter", "rtpu_scheduler_lease_reused_total",
    "Completions whose worker lease was handed straight to the next "
    "pipelined task (no scheduler round trip)")
M_PIPELINE_DEPTH = define(
    "gauge", "rtpu_scheduler_pipeline_depth",
    "Tasks currently leased onto busy workers beyond their running "
    "task, summed over the node's workers (sampled)")
M_SUBMIT_BATCH = define(
    "histogram", "rtpu_scheduler_submit_batch_specs",
    "Task/actor-call specs per coalesced SUBMIT_BATCH frame admitted "
    "by the dispatcher as one scheduling pass",
    buckets=(2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0))
M_STORE_PUTS = define(
    "counter", "rtpu_object_store_puts_total",
    "Objects sealed into the local object store")
M_STORE_PUT_BYTES = define(
    "counter", "rtpu_object_store_put_bytes_total",
    "Bytes sealed into the local object store")
M_STORE_GET_BYTES = define(
    "counter", "rtpu_object_store_get_bytes_total",
    "Bytes served to get() callers from this node")
M_STORE_HITS = define(
    "counter", "rtpu_object_store_hits_total",
    "get() lookups resolved immediately from the directory/store")
M_STORE_MISSES = define(
    "counter", "rtpu_object_store_misses_total",
    "get() lookups that had to wait for the object to appear")
M_STORE_USED = define(
    "gauge", "rtpu_object_store_used_bytes",
    "Object store bytes in use (sampled)")
M_STORE_CAPACITY = define(
    "gauge", "rtpu_object_store_capacity_bytes",
    "Object store capacity (sampled)")
M_STORE_FILL = define(
    "gauge", "rtpu_object_store_fill_ratio",
    "used_bytes / capacity_bytes of the local store (sampled)")
M_STORE_OBJECTS = define(
    "gauge", "rtpu_object_store_objects",
    "Live objects in the local store (sampled)")
M_STORE_SPILLED = define(
    "gauge", "rtpu_object_store_spilled_objects",
    "Objects spilled to disk since node start (sampled)")
M_STORE_SHM_BYTES = define(
    "gauge", "rtpu_object_store_shm_bytes",
    "Bytes resident in shared memory (arena blocks + POSIX segments) "
    "for this node's store (sampled)")
M_STORE_ARENA_FILL = define(
    "gauge", "rtpu_object_store_arena_fill_ratio",
    "arena_used_bytes / arena_capacity_bytes of the node's shm arena "
    "(sampled; 0 when the native arena is unavailable)")
M_OBJ_SPILLED_BYTES = define(
    "counter", "rtpu_object_spilled_bytes_total",
    "Bytes written to spill files under memory pressure")
M_OBJ_RESTORED = define(
    "counter", "rtpu_object_restored_total",
    "Spilled objects restored on demand (get/task-arg/pull)")
M_OBJ_CALLSITES = define(
    "counter", "rtpu_object_callsites_recorded_total",
    "Creation callsites captured for puts / task returns / actor "
    "creations (object_callsite_enabled provenance plane)")
M_OBJ_LEAKED = define(
    "gauge", "rtpu_object_leaked_objects",
    "Objects the control-plane leak sweep currently flags: every ref "
    "holder lives on a dead node, or pinned with zero holders past "
    "memory_leak_pinned_ttl_s")
M_GCS_RPC_LATENCY = define(
    "histogram", "rtpu_gcs_rpc_latency_seconds",
    "Round-trip latency of synchronous control-plane RPCs, tagged by "
    "method")
M_GCS_RPC_TOTAL = define(
    "counter", "rtpu_gcs_rpc_total",
    "Control-plane RPCs issued, tagged method and kind=call|cast")
M_NODE_RSS = define(
    "gauge", "rtpu_node_rss_bytes",
    "Resident set size of the node service process (sampled)")
M_NODE_LOAD = define(
    "gauge", "rtpu_node_cpu_load_1m",
    "Host 1-minute load average (sampled)")
M_NODE_WORKERS = define(
    "gauge", "rtpu_node_workers",
    "Worker processes attached to this node (sampled)")
M_HBM_USED = define(
    "gauge", "rtpu_device_hbm_bytes_in_use",
    "Accelerator memory in use per JAX device (sampled; absent on "
    "CPU-only JAX)")
M_HBM_LIMIT = define(
    "gauge", "rtpu_device_hbm_bytes_limit",
    "Accelerator memory limit per JAX device (sampled; absent on "
    "CPU-only JAX)")
M_JAX_COMPILES = define(
    "counter", "rtpu_jax_compiles_total",
    "JAX compilation events observed in this process")
M_DROPPED_SERIES = define(
    "counter", "rtpu_telemetry_dropped_series_total",
    "Metric series dropped by the control plane (cardinality cap or "
    "histogram bucket conflicts); synthesized at export from the "
    "plane's drop counter")
# wire transport (``protocol.Connection``): recorded per writer flush /
# receive wakeup, never per message — the hot path stays lock-cheap
_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
M_TRANSPORT_FLUSH_FRAMES = define(
    "histogram", "rtpu_transport_flush_frames",
    "Messages coalesced per connection-writer flush",
    buckets=_BATCH_BUCKETS)
M_TRANSPORT_RECV_FRAMES = define(
    "histogram", "rtpu_transport_recv_frames",
    "Messages decoded per receive wakeup (burst dispatch)",
    buckets=_BATCH_BUCKETS)
M_TRANSPORT_SEND_BYTES = define(
    "counter", "rtpu_transport_send_bytes_total",
    "Bytes written to control-plane sockets (frames incl. headers)")
M_TRANSPORT_OOB_BYTES = define(
    "counter", "rtpu_transport_oob_bytes_total",
    "Payload bytes shipped out-of-band as zero-copy iovecs")
M_TRANSPORT_QUEUE_STALLS = define(
    "counter", "rtpu_transport_queue_stalls_total",
    "Producer blocks on a full connection send queue (backpressure)")


def attach_node(node) -> None:
    """Register a NodeService for host/store sampling and direct-plane
    flushing; starts the per-process sampler thread on first call."""
    global _sampler_started
    with _runtime_lock:
        if node not in _nodes:
            _nodes.append(node)
        start = not _sampler_started
        _sampler_started = True
    _ensure_flusher()
    if start:
        t = threading.Thread(target=_sample_loop, daemon=True,
                             name="rtpu-telemetry-sampler")
        t.start()


def detach_node(node) -> None:
    with _runtime_lock:
        if node in _nodes:
            _nodes.remove(node)


def _sample_loop() -> None:
    while True:
        time.sleep(max(CONFIG.telemetry_sample_interval_ms, 250) / 1000.0)
        try:
            sample_once()
            flush()
        except Exception:   # noqa: BLE001 — a bad sample is a gap
            pass


def sample_once() -> None:
    """One host + store + device sampling pass (called by the sampler
    thread; separately callable for tests)."""
    with _runtime_lock:
        nodes = [n for n in _nodes if not getattr(n, "dead", False)]
    for node in nodes:
        tags = (("node", node.node_id.hex()[:12]),)
        try:
            stats = node.store.stats()
            used = stats.get("used_bytes", 0)
            cap = stats.get("capacity_bytes", 0) or 1
            gauge_set(M_STORE_USED, float(used), tags)
            gauge_set(M_STORE_CAPACITY, float(cap), tags)
            gauge_set(M_STORE_FILL, used / cap, tags)
            gauge_set(M_STORE_OBJECTS, float(stats.get("num_objects", 0)),
                      tags)
            gauge_set(M_STORE_SPILLED, float(stats.get("num_spilled", 0)),
                      tags)
            gauge_set(M_STORE_SHM_BYTES, float(stats.get("shm_bytes", 0)),
                      tags)
            gauge_set(M_STORE_ARENA_FILL,
                      (stats.get("arena_used_bytes", 0)
                       / (stats.get("arena_capacity_bytes", 0) or 1)),
                      tags)
        except Exception:   # noqa: BLE001
            pass
        try:
            gauge_set(M_PENDING_TASKS, float(len(node._pending)), tags)
            gauge_set(M_NODE_WORKERS, float(len(node._workers)), tags)
            gauge_set(M_PIPELINE_DEPTH,
                      float(sum(len(w.pipeline)
                                for w in list(node._workers.values()))),
                      tags)
        except Exception:   # noqa: BLE001
            pass
        _sample_host(tags)
    sample_devices()


def _sample_host(tags: tuple) -> None:
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        gauge_set(M_NODE_RSS, float(rss_pages * os.sysconf("SC_PAGE_SIZE")),
                  tags)
    except (OSError, ValueError, IndexError):
        pass
    try:
        gauge_set(M_NODE_LOAD, os.getloadavg()[0], tags)
    except OSError:
        pass


def sample_devices() -> int:
    """Record per-device HBM gauges via ``device.memory_stats()``.
    Returns the number of devices that reported stats; 0 (and records
    nothing) on CPU-only JAX or when jax was never imported. Never
    raises."""
    if "jax" not in sys.modules:
        return 0
    _install_jax_compile_listener()
    reported = 0
    try:
        jax = sys.modules["jax"]
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:   # noqa: BLE001 — backend-dependent
                stats = None
            if not stats:
                continue
            used = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            tags = (("device", f"{dev.platform}:{dev.id}"),)
            if used is not None:
                gauge_set(M_HBM_USED, float(used), tags)
                reported += 1
            if limit is not None:
                gauge_set(M_HBM_LIMIT, float(limit), tags)
    except Exception:   # noqa: BLE001 — sampling must never raise
        return reported
    return reported


def _install_jax_compile_listener() -> None:
    """Count JAX compile events (once per process, only when jax is
    already imported — telemetry never pulls jax in itself)."""
    global _jax_listener_installed
    if _jax_listener_installed or "jax" not in sys.modules:
        return
    _jax_listener_installed = True
    try:
        from jax import monitoring

        def _on_event(event: str, **kw) -> None:
            if "compile" in event:
                counter_inc(M_JAX_COMPILES)

        monitoring.register_event_listener(_on_event)
    except Exception:   # noqa: BLE001 — older/newer jax API drift
        pass


# guarded-by plane: wrap the declared module-level registries in
# checking proxies (no-op when RTPU_FIELDSAN is off)
fieldsan.instrument_module(globals(), "telemetry")
