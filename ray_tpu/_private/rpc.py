"""Request/reply plumbing over a framed Connection.

Equivalent role to the reference's gRPC client stubs
(``src/ray/rpc/grpc_client.h``): correlate req_ids with futures, own a
reader thread, and hand non-reply frames to a push handler. Used by the
remote GCS client and node→node peer channels; the CoreClient keeps its
own (older) copy of this pattern.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Tuple

from . import locksan
from . import protocol as P


class RpcChannel:
    """Thread-safe request/reply over one Connection.

    Replies are any ``(op, (req_id, value))`` frame whose op is in
    ``reply_ops``; everything else goes to ``on_push(op, payload)``.
    """

    def __init__(self, conn: P.Connection,
                 on_push: Optional[Callable[[int, Any], None]] = None,
                 on_close: Optional[Callable[[], None]] = None,
                 reply_ops: Tuple[int, ...] = (P.INFO_REPLY,)):
        self._conn = conn
        self._on_push = on_push
        self._on_close = on_close
        self._reply_ops = set(reply_ops)
        self._futures: Dict[int, Future] = {}
        self._lock = locksan.lock("rpc.futures")
        self._next_req = 1
        self._closed = threading.Event()
        conn.on_send_error = self._on_send_error
        self._thread = threading.Thread(target=self._read_loop,
                                        name="rtpu-rpc-reader", daemon=True)
        self._thread.start()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def _read_loop(self) -> None:
        while True:
            # burst receive: one socket wakeup dispatches every frame
            # the peer's writer coalesced (replies resolve their futures
            # back-to-back instead of one wakeup each)
            msgs = self._conn.recv_many()
            if msgs is None:
                self._fail_all(ConnectionError("rpc channel closed"))
                if self._on_close is not None:
                    try:
                        self._on_close()
                    except Exception:
                        pass
                return
            for msg in msgs:
                self._dispatch_one(msg)

    def _dispatch_one(self, msg: Tuple[int, Any]) -> None:
        op, payload = msg
        if op in self._reply_ops:
            req_id, value = payload
            with self._lock:
                fut = self._futures.pop(req_id, None)
            if fut is not None:
                fut.set_result(value)
        elif op == P.ERROR_REPLY:
            req_id, err = payload
            with self._lock:
                fut = self._futures.pop(req_id, None)
            if fut is not None:
                from . import serialization as ser
                fut.set_exception(ser.from_bytes(err))
        elif self._on_push is not None:
            try:
                self._on_push(op, payload)
            except Exception:
                pass

    def _on_send_error(self, msg, exc: BaseException) -> None:
        P.fail_dropped_request(msg, exc, self._lock, self._futures)

    def _fail_all(self, exc: Exception) -> None:
        with self._lock:
            self._closed.set()
            futures = list(self._futures.values())
            self._futures.clear()
        for fut in futures:
            if not fut.done():
                fut.set_exception(exc)

    def request(self, op: int, make_payload: Callable[[int], Any],
                timeout: Optional[float] = None) -> Any:
        """Synchronous call: sends ``(op, make_payload(req_id))``, waits
        for the correlated reply."""
        return self.request_async(op, make_payload).result(timeout=timeout)

    def request_async(self, op: int,
                      make_payload: Callable[[int], Any]) -> Future:
        """Send now, await later — several requests can ride the channel
        concurrently (windowed chunk pulls overlap RTTs this way)."""
        fut: Future = Future()
        with self._lock:
            if self._closed.is_set():
                raise ConnectionError("rpc channel is closed")
            req_id = self._next_req
            self._next_req += 1
            self._futures[req_id] = fut
        self._conn.send((op, make_payload(req_id)))
        return fut

    def send(self, op: int, payload: Any) -> None:
        """Fire-and-forget."""
        self._conn.send((op, payload))

    def close(self) -> None:
        self._closed.set()
        self._conn.close()
