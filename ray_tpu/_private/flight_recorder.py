"""Collective flight recorder: always-on, per-process, lock-light.

Reference analogue: the NCCL flight recorder ("Collective Communication
for 100k+ GPUs": at scale the dominant operational cost of collectives
is diagnosing stragglers and hangs). Every schedule in
``comm/collective.py`` and every mailbox op in
``_private/coll_transport.py`` feeds two structures:

- a fixed-size **event ring** (``flight_recorder_capacity`` slots;
  0 disables recording). Appends are lock-free: an ``itertools.count``
  hands each writer a distinct slot and a CPython list-item store is
  atomic — no allocation beyond the event tuple, no RPC, cheap enough
  to stay on for every chunk of every collective.
- a per-(group, op-key) **watermark table** of in-flight ops: chunks
  sent/consumed, the last phase touched, and the exact mailbox key the
  rank is currently blocked waiting on. Ops run on one rank thread per
  group, so the per-op record needs no lock; the table itself takes a
  short lock only at op begin/end and snapshot time (never on the
  chunk path).

``progress_snapshot()`` is the body of a ``COLL_PROGRESS`` reply —
answered on connection reader threads like ``STACK_DUMP``, so a rank
wedged *inside* a collective still answers. ``diagnose()`` is the
cluster-wide half: given every rank's snapshot it diffs watermarks and
names the verdict — **dead rank** (its process answered nothing),
**lost chunk** (a sender logged the send, the receiver never saw the
delivery — naming the edge), or **lagging rank** (lowest watermark).
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import locksan
from . import telemetry
from .config import CONFIG

M_INFLIGHT_OPS = telemetry.define(
    "gauge", "rtpu_collective_inflight_ops",
    "Collective calls this process has started but not finished "
    "(flight-recorder watermark table size)")

# event kinds in the ring
EV_SEND = "send"
EV_DELIVER = "deliver"
EV_RECV = "recv"
EV_BEGIN = "begin"
EV_END = "end"

_lock = locksan.lock("coll.recorder")
_ring: List[Any] = []                 # event tuples, overwritten in place
_idx = itertools.count()              # thread-safe slot allocator
_groups: Dict[Tuple[str, str], dict] = {}   # (group, epoch) -> membership
_inflight: Dict[tuple, dict] = {}     # (group, okey) -> op record
_done: deque = deque(maxlen=256)      # completed op records (timeline)

# how many sent/delivered keys per in-flight op a snapshot ships for
# the lost-chunk cross-reference
_SNAP_KEYS_PER_OP = 64
_SNAP_RING_EVENTS = 256


def enabled() -> bool:
    return CONFIG.flight_recorder_capacity > 0


def _record(ev: tuple) -> None:
    """Lock-free ring append (see module docstring)."""
    cap = CONFIG.flight_recorder_capacity
    if cap <= 0:
        return
    ring = _ring
    if len(ring) != cap:
        ring = _resize(cap)
    ring[next(_idx) % len(ring)] = ev


def _resize(cap: int) -> list:
    global _ring
    with _lock:
        if len(_ring) != cap:
            _ring = [None] * cap
        return _ring


def parse_key(key: tuple) -> Tuple[Optional[tuple], str]:
    """Map one transport mailbox key to its recorder op-key and phase.

    Schedule keys are ``(group, epoch, seq:int, *tail)`` where the tail
    mixes phase strings ("rs"/"ag"/"hx"/...) with segment/chunk ints;
    p2p send/recv keys are ``(group, epoch, "p2p", src, dst, tag, seq)``.
    Phase strings come from a small fixed literal set, so the joined
    phase label is bounded-cardinality (safe as a metric tag)."""
    try:
        if len(key) < 3:
            return None, "other"
        if key[2] == "p2p":
            return (key[0], ("p2p",) + tuple(key[3:7])), "p2p"
        phase = ".".join(s for s in key[3:] if isinstance(s, str))
        return (key[0], key[2]), phase or "op"
    except Exception:   # noqa: BLE001 — malformed key: never break sends
        return None, "other"


# ------------------------------------------------------------ op lifecycle

def register_group(group: str, epoch: str, rank: int, world: int,
                   endpoints: Optional[List[Any]]) -> None:
    """Membership registry: which rank of which group THIS process is,
    plus every rank's endpoint (hex) so a diagnosis can name a dead
    rank's home. Fed by ``init_collective_group``."""
    eps = None
    if endpoints is not None:
        eps = [[e[0].hex()[:12], e[1].hex()[:12]] if e is not None else None
               for e in endpoints]
    with _lock:
        _groups[(group, epoch)] = {"rank": rank, "world": world,
                                   "endpoints": eps}


def unregister_group(group: str, epoch: str) -> None:
    with _lock:
        _groups.pop((group, epoch), None)
        for k in [k for k in _inflight if k[0] == group]:
            del _inflight[k]
    telemetry.gauge_set(M_INFLIGHT_OPS, float(len(_inflight)))


def op_begin(group: str, epoch: str, okey, op: str, algo: str,
             nbytes: int, world: int, rank: int) -> None:
    """One public collective call starts. ``okey`` is the sequence
    number for schedule ops, or the ("p2p", src, dst, tag, seq) tuple
    for direct send/recv."""
    if not enabled():
        return
    now = time.monotonic()
    rec = {"group": group, "epoch": epoch, "key": okey, "op": op,
           "algo": algo, "nbytes": int(nbytes), "world": world,
           "rank": rank, "start": time.time(), "start_mono": now,
           "sent": 0, "sent_bytes": 0, "recv": 0, "recv_bytes": 0,
           "last_phase": "", "last_mono": now,
           "waiting": None, "waiting_since": 0.0,
           "done": False, "error": None}
    _record((now, EV_BEGIN, (group, okey), op, algo, int(nbytes)))
    with _lock:
        _inflight[(group, okey)] = rec
    telemetry.gauge_set(M_INFLIGHT_OPS, float(len(_inflight)))


def op_error(group: str, okey, error: str) -> None:
    """Mark an op failed but KEEP it in the watermark table: the
    diagnosis fan-out that follows a TimeoutError must still see this
    rank's record (both survivors time out near-simultaneously — if
    each dropped its record before querying, nobody would have
    evidence). ``op_end`` retires it after diagnosis."""
    rec = _inflight.get((group, okey))
    if rec is not None:
        rec["error"] = error


def op_end(group: str, okey, error: Optional[str] = None) -> None:
    now = time.monotonic()
    with _lock:
        rec = _inflight.pop((group, okey), None)
    if rec is None:
        return
    rec["done"] = True
    rec["end_mono"] = now
    rec["dur"] = max(now - rec["start_mono"], 1e-6)
    if error is not None:
        rec["error"] = error
    _record((now, EV_END, (group, okey), rec["op"],
             rec["error"] or "ok", rec["nbytes"]))
    _done.append(rec)
    telemetry.gauge_set(M_INFLIGHT_OPS, float(len(_inflight)))


# ------------------------------------------------- chunk-path hooks (hot)

def note_send(key: tuple, nbytes: int) -> None:
    """Rank thread queued one chunk onto the node link."""
    if not enabled():
        return
    okey, phase = parse_key(key)
    _record((time.monotonic(), EV_SEND, key, nbytes))
    rec = _inflight.get(okey) if okey is not None else None
    if rec is not None:
        rec["sent"] += 1
        rec["sent_bytes"] += nbytes
        rec["last_phase"] = phase
        rec["last_mono"] = time.monotonic()


def note_deliver(key: tuple, nbytes: int) -> None:
    """Reader thread deposited one chunk into the mailbox. Ring only —
    the reader must stay lean (threading-model rule 4), and consumption
    (the true watermark) is recorded by ``note_recv`` on the rank
    thread."""
    if not enabled():
        return
    _record((time.monotonic(), EV_DELIVER, key, nbytes))


def note_wait(key: tuple) -> None:
    """Rank thread is about to block on ``key``. The key stays in the
    record until the chunk arrives — on a hang it IS the watermark
    ('phase rs, waiting on chunk 7'), and the lost-chunk diagnosis
    cross-references it against senders' logs."""
    if not enabled():
        return
    okey, _phase = parse_key(key)
    rec = _inflight.get(okey) if okey is not None else None
    if rec is not None:
        rec["waiting"] = key
        rec["waiting_since"] = time.time()


def note_recv(key: tuple, nbytes: int) -> None:
    """Rank thread consumed the awaited chunk."""
    if not enabled():
        return
    okey, phase = parse_key(key)
    _record((time.monotonic(), EV_RECV, key, nbytes))
    rec = _inflight.get(okey) if okey is not None else None
    if rec is not None:
        rec["recv"] += 1
        rec["recv_bytes"] += nbytes
        rec["last_phase"] = phase
        rec["last_mono"] = time.monotonic()
        rec["waiting"] = None


# ------------------------------------------------------------- snapshots

def _key_list(key) -> Optional[list]:
    if key is None:
        return None
    if isinstance(key, tuple):
        return [_key_list(k) if isinstance(k, tuple) else k for k in key]
    return key


def _key_tuple(key) -> Optional[tuple]:
    if key is None:
        return None
    if isinstance(key, (list, tuple)):
        return tuple(_key_tuple(k) if isinstance(k, (list, tuple)) else k
                     for k in key)
    return key


def _shape_op(rec: dict) -> dict:
    out = dict(rec)
    out["key"] = _key_list(out["key"]) if isinstance(
        out["key"], tuple) else out["key"]
    out["waiting"] = _key_list(out.get("waiting"))
    return out


def watermark(rec: dict) -> str:
    """Human-readable high-water mark of one op record: 'phase rs,
    chunk 7 sent / 6 delivered, waiting on (...)'."""
    parts = [f"phase {rec.get('last_phase') or 'start'}",
             f"{rec.get('sent', 0)} chunk(s) sent",
             f"{rec.get('recv', 0)} delivered"]
    w = rec.get("waiting")
    if w:
        parts.append(f"waiting on {tuple(w)!r}")
    return ", ".join(parts)


def progress_snapshot(**ids) -> dict:
    """One process's COLL_PROGRESS reply body: group membership,
    in-flight op watermarks, recently completed ops, the recent event
    ring (bounded), and per-in-flight-op sent/delivered key lists for
    the lost-chunk cross-reference. ``ids`` carries identity tags."""
    with _lock:
        groups = [{"group": gk[0], "epoch": gk[1], **info}
                  for gk, info in _groups.items()]
        inflight = [_shape_op(rec) for rec in _inflight.values()]
        done = [_shape_op(rec) for rec in list(_done)[-64:]]
        live_keys = {(rec["group"], rec["key"])
                     for rec in _inflight.values()}
    # ring scan outside the lock: slots hold immutable tuples, and a
    # torn read across an overwrite just drops one event
    events = [e for e in _ring if e is not None]
    events.sort(key=lambda e: e[0])
    sent_keys: Dict[int, List[list]] = {}
    delivered_keys: Dict[int, List[list]] = {}
    okey_index = {k: i for i, k in enumerate(live_keys)}
    # NEWEST events first: the key a stuck receiver is blocked on pairs
    # with a sender's most RECENT sends, so when an op has issued more
    # than the per-op cap the tail — not the head — must survive
    for ev in reversed(events):
        if ev[1] not in (EV_SEND, EV_DELIVER):
            continue
        okey, _phase = parse_key(ev[2])
        idx = okey_index.get(okey)
        if idx is None:
            continue
        bucket = sent_keys if ev[1] == EV_SEND else delivered_keys
        lst = bucket.setdefault(idx, [])
        if len(lst) < _SNAP_KEYS_PER_OP:
            lst.append(_key_list(ev[2]))
    recent = [{"ts": e[0], "kind": e[1], "key": _key_list(e[2]),
               "info": _key_list(e[3]) if isinstance(e[3], tuple)
               else e[3],
               "extra": list(e[4:])} for e in events[-_SNAP_RING_EVENTS:]]
    return {"now": time.time(), "groups": groups, "inflight": inflight,
            "done": done, "recent": recent,
            "op_keys": [[g, _key_list(k)] for (g, k) in okey_index],
            "sent_keys": sent_keys, "delivered_keys": delivered_keys,
            **ids}


def reset() -> None:
    """Session teardown: the next init() must not inherit this session's
    records (the timeline golden test depends on a clean ring)."""
    global _ring
    with _lock:
        _ring = []
        _groups.clear()
        _inflight.clear()
        _done.clear()


# ------------------------------------------------------------- diagnosis

def _op_sort_key(okey) -> tuple:
    return (0, okey) if isinstance(okey, int) else (1, str(okey))


def diagnose(per_node: Dict[str, Any]) -> dict:
    """Cluster-wide hang diagnosis over every rank's progress snapshot
    (``per_node``: node hex -> [snapshot, ...] as collected by
    ``node.collective_health``). For each op some rank is still inside,
    name the verdict, most specific first:

    1. **dead_rank** — a member rank whose process answered nothing
       (no snapshot claims that rank of that group: SIGKILLed worker,
       closed endpoint conn, dead node).
    2. **lost_chunk** — a receiver has been blocked on a key some
       sender logged sending (and the receiver never logged a deliver):
       the edge src->dst dropped it.
    3. **lagging_rank** — the rank with the lowest watermark: hasn't
       started the op at all, or has consumed the fewest chunks.
    """
    snaps: List[dict] = []
    for dumps in (per_node or {}).values():
        for s in dumps or []:
            if isinstance(s, dict):
                snaps.append(s)

    present: Dict[tuple, set] = {}        # (group, epoch) -> ranks replied
    worlds: Dict[tuple, int] = {}
    endpoints: Dict[tuple, Any] = {}
    for s in snaps:
        for g in s.get("groups", ()):
            gk = (g["group"], g["epoch"])
            present.setdefault(gk, set()).add(g["rank"])
            worlds[gk] = max(worlds.get(gk, 0), g.get("world", 0))
            if g.get("endpoints"):
                endpoints[gk] = g["endpoints"]

    # (group, epoch, okey) -> {rank: (state, record)}, plus two key
    # indexes for the lost-chunk cross-reference: mailbox key -> ranks
    # that logged sending it / ranks whose reader logged its delivery
    ops: Dict[tuple, Dict[int, tuple]] = {}
    sender_of: Dict[tuple, List[int]] = {}
    delivered_to: Dict[tuple, set] = {}
    for s in snaps:
        for rec in s.get("inflight", ()):
            k = (rec["group"], rec["epoch"], _key_tuple(rec["key"]))
            ops.setdefault(k, {})[rec["rank"]] = ("inflight", rec)
        for rec in s.get("done", ()):
            k = (rec["group"], rec["epoch"], _key_tuple(rec["key"]))
            ops.setdefault(k, {}).setdefault(rec["rank"], ("done", rec))
        group_rank = {g["group"]: g["rank"] for g in s.get("groups", ())}
        op_keys = [(g, _key_tuple(k)) for g, k in s.get("op_keys", ())]

        def ranks_for(index_table, out, s_ranks=group_rank,
                      s_keys=op_keys):
            for idx, keys in (index_table or {}).items():
                idx = int(idx)
                if idx >= len(s_keys):
                    continue
                rank = s_ranks.get(s_keys[idx][0], -1)
                for key in keys:
                    out(_key_tuple(key), rank)

        ranks_for(s.get("sent_keys"),
                  lambda k, r: sender_of.setdefault(k, []).append(r))
        ranks_for(s.get("delivered_keys"),
                  lambda k, r: delivered_to.setdefault(k, set()).add(r))

    now = max([s.get("now", 0.0) for s in snaps], default=time.time())
    shaped_ops: List[dict] = []
    verdicts: List[dict] = []
    for (group, epoch, okey), by_rank in sorted(
            ops.items(), key=lambda kv: (kv[0][0],
                                         _op_sort_key(kv[0][2]))):
        stuck = {r: rec for r, (st, rec) in by_rank.items()
                 if st == "inflight"}
        sample = next(iter(by_rank.values()))[1]
        world = worlds.get((group, epoch)) or sample.get("world", 0)
        op_row = {
            "group": group, "epoch": epoch,
            "seq": okey if isinstance(okey, int) else list(okey),
            "op": sample.get("op"), "algo": sample.get("algo"),
            "nbytes": sample.get("nbytes"), "world": world,
            "done_ranks": sorted(r for r, (st, _rec)
                                 in by_rank.items() if st == "done"),
            "stuck_ranks": {r: watermark(rec)
                            for r, rec in sorted(stuck.items())},
        }
        shaped_ops.append(op_row)
        if not stuck:
            continue
        label = (f"collective {sample.get('op')!r} group={group!r} "
                 f"seq={op_row['seq']} ({sample.get('algo')}, "
                 f"{len(op_row['done_ranks'])}/{world} ranks finished)")
        member_ranks = set(range(world)) if world else set(by_rank)
        replied = present.get((group, epoch), set())
        dead = sorted(member_ranks - replied)
        verdict: Optional[dict] = None
        if dead:
            eps = endpoints.get((group, epoch))
            where = ""
            if eps and dead[0] < len(eps) and eps[dead[0]]:
                where = (f" (endpoint node={eps[dead[0]][0]} "
                         f"worker={eps[dead[0]][1]} answered nothing — "
                         "process dead or connection closed)")
            verdict = {"verdict": "dead_rank", "rank": dead[0],
                       "message": f"{label}: dead rank {dead[0]}{where}; "
                                  "survivors are parked at "
                                  + "; ".join(
                                      f"rank {r}: {w}" for r, w in
                                      op_row["stuck_ranks"].items())}
        if verdict is None:
            # lost chunk: a stuck receiver waits on a key somebody
            # logged SENDING whose delivery the receiver's own reader
            # never logged — a key merely in flight (delivered after
            # the receiver's snapshot instant) is not lost
            for r, rec in sorted(stuck.items()):
                wkey = _key_tuple(rec.get("waiting"))
                since = rec.get("waiting_since") or 0.0
                if wkey is None or now - since < 1.0:
                    continue
                if r in delivered_to.get(wkey, ()):
                    continue
                senders = [s for s in sender_of.get(wkey, ()) if s != r]
                if senders:
                    verdict = {
                        "verdict": "lost_chunk", "rank": r,
                        "message": (f"{label}: lost chunk on edge "
                                    f"rank {senders[0]} -> rank {r} — "
                                    f"sender logged the send of "
                                    f"{wkey!r} but rank {r} never saw "
                                    "the delivery")}
                    break
        if verdict is None:
            not_started = sorted(r for r in (member_ranks & replied)
                                 if r not in by_rank)
            if not_started:
                lag = not_started[0]
                verdict = {"verdict": "lagging_rank", "rank": lag,
                           "message": (f"{label}: lagging rank {lag} — "
                                       "it has not entered this "
                                       "collective yet; peers are at "
                                       + "; ".join(
                                           f"rank {r}: {w}" for r, w in
                                           op_row["stuck_ranks"].items()))}
            else:
                lag, lag_rec = min(
                    stuck.items(),
                    key=lambda kv: (kv[1].get("recv", 0)
                                    + kv[1].get("sent", 0)))
                verdict = {"verdict": "lagging_rank", "rank": lag,
                           "message": (f"{label}: lagging rank {lag} "
                                       f"({watermark(lag_rec)})")}
        verdict.update({"group": group, "epoch": epoch,
                        "seq": op_row["seq"], "op": sample.get("op"),
                        "phase": next(
                            (rec.get("last_phase") or "start"
                             for rec in stuck.values()), "start")})
        verdicts.append(verdict)
    members = [{"group": g["group"], "epoch": g["epoch"],
                "rank": g["rank"], "worker_id": s.get("worker_id")}
               for s in snaps for g in s.get("groups", ())]
    return {"ops": shaped_ops, "verdicts": verdicts,
            "members": members, "processes": len(snaps)}
