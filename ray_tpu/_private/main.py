"""Node process entrypoint: ``python -m ray_tpu._private.main``.

Starts one node service in this OS process, either as the head (hosting
the GCS service) or joining an existing cluster over TCP. Equivalent
role to the reference's ``ray start --head`` / ``ray start --address=``
(``python/ray/scripts/scripts.py`` start command + ``node.py`` process
supervision).

On readiness a JSON line ``{"node_id": ..., "gcs_port": ...,
"node_address": ...}`` is written to ``--ready-file`` (and stdout) so a
parent process (``cluster_utils.Cluster(process_isolated=True)`` or an
operator script) can discover ports and identity.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ray_tpu node")
    ap.add_argument("--head", action="store_true",
                    help="host the GCS service in this process")
    ap.add_argument("--address", default=None,
                    help="host:port of the head GCS (join an existing cluster)")
    ap.add_argument("--gcs-port", type=int, default=0,
                    help="head only: TCP port for the GCS service (0 = auto)")
    ap.add_argument("--node-port", type=int, default=0,
                    help="TCP port for this node service (0 = auto)")
    ap.add_argument("--advertise-host", default="127.0.0.1")
    ap.add_argument("--num-cpus", type=float, default=None)
    ap.add_argument("--num-tpus", type=float, default=None)
    ap.add_argument("--resources", default="{}",
                    help="extra custom resources as JSON")
    ap.add_argument("--labels", default="{}")
    ap.add_argument("--session-dir", default=None)
    ap.add_argument("--ready-file", default=None)
    ap.add_argument("--job-port", type=int, default=0,
                    help="head only: REST port for job submission (0 = auto)")
    ap.add_argument("--dashboard-port", type=int, default=0,
                    help="head only: dashboard HTTP port (0 = auto, "
                         "-1 = disabled)")
    ap.add_argument("--http-host", default="127.0.0.1",
                    help="bind address for the dashboard + job REST "
                         "servers (default loopback; set 0.0.0.0 to "
                         "expose on all interfaces)")
    ap.add_argument("--storage", default=None,
                    help="head only: GCS persistence path (journal file "
                         "or directory); durable KV/jobs/PG metadata "
                         "survives a head restart")
    args = ap.parse_args(argv)

    if bool(args.head) == bool(args.address):
        ap.error("exactly one of --head / --address is required")

    # node processes never own the TPU; the driver/trainer does
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from .gcs import GlobalControlPlane
    from .gcs_service import GcsServer, RemoteControlPlane
    from .node import NodeService

    session_dir = args.session_dir or tempfile.mkdtemp(prefix="rtpu_node_")
    resources = dict(json.loads(args.resources))
    resources.setdefault(
        "CPU", float(args.num_cpus if args.num_cpus is not None
                     else os.cpu_count() or 4))
    if args.num_tpus is not None:
        resources.setdefault("TPU", float(args.num_tpus))

    gcs_server = None
    if args.head:
        from .gcs_storage import open_storage
        plane = GlobalControlPlane(storage=open_storage(args.storage))
        # bound journal growth from the previous life before serving
        if args.storage:
            plane.compact_storage()
        gcs_server = GcsServer(plane, port=args.gcs_port)
        gcs = plane
        gcs_port = gcs_server.port
    else:
        gcs = RemoteControlPlane(args.address)
        gcs_port = int(args.address.rsplit(":", 1)[1])

    node = NodeService(gcs, session_dir, resources)
    node.start(labels=json.loads(args.labels), tcp_port=args.node_port,
               advertise_host=args.advertise_host)
    job_rest = None
    job_port = None
    if args.head:
        # drivers attaching by GCS address find the head node here
        gcs.kv_put(b"__rtpu_head_node",
                   json.dumps({"node_id": node.node_id.hex(),
                               "address": node.tcp_address,
                               "host": node.host,
                               "shm_probe": [node.shm_probe_path,
                                             node.shm_probe_token]}).encode())
        # advertise an address something actually listens on: a loopback
        # bind must not be advertised as the external advertise_host
        http_adv = (args.advertise_host if args.http_host == "0.0.0.0"
                    else args.http_host)
        # job submission API (reference: dashboard job head)
        from ..job.http_server import JobRestServer
        from ..job.manager import JobManager
        manager = JobManager(
            gcs, cluster_address=f"{args.advertise_host}:{gcs_port}",
            session_dir=session_dir)
        job_rest = JobRestServer(manager, host=args.http_host,
                                 port=args.job_port)
        job_rest.start()
        job_port = job_rest.port
        gcs.kv_put(b"__rtpu_job_api",
                   f"{http_adv}:{job_port}".encode())

    dashboard = None
    dashboard_port = None
    if args.head and args.dashboard_port >= 0:
        from ..dashboard import DashboardServer
        dashboard = DashboardServer(node, job_manager=manager,
                                    host=args.http_host,
                                    port=args.dashboard_port)
        dashboard.start()
        dashboard_port = dashboard.port
        gcs.kv_put(b"__rtpu_dashboard",
                   f"{http_adv}:{dashboard_port}".encode())

    ready = {"node_id": node.node_id.hex(), "gcs_port": gcs_port,
             "node_address": node.tcp_address, "session_dir": session_dir,
             "job_port": job_port, "dashboard_port": dashboard_port}
    line = json.dumps(ready)
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(line)
        os.replace(tmp, args.ready_file)
    print(line, flush=True)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        while not stop.wait(0.5):
            if not args.head and getattr(gcs, "closed", False):
                # head is gone; a node without a control plane is useless
                break
    finally:
        node.stop()
        if dashboard is not None:
            dashboard.stop()
        if job_rest is not None:
            job_rest.stop()
        if gcs_server is not None:
            gcs_server.stop()
        if args.head:
            gcs.close_storage()
    return 0


if __name__ == "__main__":
    sys.exit(main())
