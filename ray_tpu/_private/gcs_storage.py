"""Pluggable persistence for the control plane's durable tables.

Reference analogue: ``src/ray/gcs/store_client/`` — the GCS writes its
metadata through a storage client (in-memory or Redis) so a restarted
GCS process recovers cluster metadata. Here the durable backend is an
append-only journal file with snapshot compaction: every durable
mutation (KV, jobs, placement-group specs) is
appended as it commits; a restarted head replays the journal and
carries on. Volatile state (object directory, refcounts, heartbeats,
task events) is intentionally NOT journaled — it describes processes
that died with the old head.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, Iterator, List, Optional, Tuple

from . import locksan

_LEN = struct.Struct("<I")

Entry = Tuple[str, str, Any]          # (table, op, payload)


class InMemoryStorage:
    """Default: nothing persists (matches the reference's in-memory
    store client)."""

    def append(self, entry: Entry) -> None:
        pass

    def load(self) -> List[Entry]:
        return []

    def compact(self, snapshot: List[Entry]) -> None:
        pass

    def close(self) -> None:
        pass


class FileStorage:
    """Append-only journal with atomic snapshot compaction.

    Layout: ``<path>`` is the journal; each record is a length-prefixed
    pickle of one Entry. ``compact()`` rewrites the file from a
    snapshot via rename, so a crash mid-compaction keeps the old
    journal intact. A torn final record (crash mid-append) is dropped
    at load.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = locksan.lock("gcs.journal")
        self._f = open(path, "ab")

    def append(self, entry: Entry) -> None:
        data = pickle.dumps(entry, protocol=5)
        with self._lock:
            self._f.write(_LEN.pack(len(data)) + data)
            self._f.flush()  # lint: allow-under-lock(the journal lock IS the append serializer; a flush outside it could interleave torn records)
            # fsync so an acknowledged durable mutation survives host
            # power loss, matching compact()'s guarantee. Appends are
            # rare (jobs/durable-KV/PGs only), so per-append cost is fine.
            os.fsync(self._f.fileno())

    def load(self) -> List[Entry]:
        out: List[Entry] = []
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return out
        off = 0
        while off + _LEN.size <= len(raw):
            (n,) = _LEN.unpack_from(raw, off)
            off += _LEN.size
            if off + n > len(raw):
                break                      # torn tail record: drop it
            try:
                out.append(pickle.loads(raw[off:off + n]))
            except Exception:              # noqa: BLE001 — corrupt record
                break
            off += n
        return out

    def compact(self, snapshot: List[Entry]) -> None:
        tmp = self.path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                for entry in snapshot:
                    data = pickle.dumps(entry, protocol=5)
                    f.write(_LEN.pack(len(data)) + data)
                f.flush()  # lint: allow-under-lock(compaction must exclude appends for the whole rewrite+rename or committed entries vanish)
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


def open_storage(spec: Optional[str]):
    """``None``/"" -> in-memory; anything else -> journal file path
    (a directory gets ``gcs.journal`` inside it)."""
    if not spec:
        return InMemoryStorage()
    path = spec
    if os.path.isdir(spec) or spec.endswith(os.sep):
        path = os.path.join(spec, "gcs.journal")
    return FileStorage(path)
