"""Networked control plane: GCS service + remote client.

Equivalent role to the reference's GCS server / client pair
(``src/ray/gcs/gcs_server/gcs_server.h``, ``gcs_service.proto:63-699``
— node/actor/PG/KV/job tables behind RPC, plus pubsub push). The head
node process hosts ``GcsServer`` wrapping the in-process
``GlobalControlPlane``; every other node process (and remote driver)
talks to it through ``RemoteControlPlane``, which duck-types the plane's
API so ``NodeService`` works unchanged over either.

Failure detection is two-channel, like the reference's
health-check-manager + connection state: a node is declared dead when
its GCS connection drops OR its heartbeats go stale
(``health_check_period_ms`` × ``health_check_failure_threshold``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import locksan
from . import protocol as P
from . import serialization as ser
from .config import CONFIG
from .gcs import GlobalControlPlane, NodeInfo
from .ids import NodeID
from .rpc import RpcChannel

# every public method of the plane a remote may invoke
_ALLOWED = frozenset({
    "register_node", "remove_node", "alive_nodes", "heartbeat", "get_node",
    "nodes_snapshot", "cluster_resources", "register_actor", "get_actor",
    "set_actor_state", "lookup_named_actor", "register_job", "finish_job",
    "kv_put", "kv_get", "kv_del", "kv_keys", "publish_location",
    "lookup_location", "drop_location", "register_pg", "get_pg",
    "remove_pg", "record_task_event", "list_task_events", "publish",
    "actors_snapshot", "directory_snapshot", "pgs_snapshot", "jobs_snapshot",
    "ref_register", "ref_drop", "drop_all_refs", "pin_task_args",
    "unpin_task_args", "pin_contained", "record_lineage", "get_lineage",
    "claim_lineage", "reconstruct_stats",
    "save_actor_checkpoint", "get_actor_checkpoint",
    "record_provenance", "objects_info", "memory_state",
    "record_cluster_event", "list_cluster_events",
    "record_spans", "list_spans", "record_metrics", "metrics_snapshot",
    "metrics_history_query", "metrics_history_dump", "lifecycle_snapshot",
    "events_stats",
    "claim_actor_reroute",
    "requeue_actor_reroute",
    "gen_update", "gen_done", "gen_consumed", "gen_get", "gen_drop",
    "register_pending_pg", "clear_pending_pg", "pending_pgs_snapshot",
})


class GcsServer:
    """TCP front for a GlobalControlPlane (runs in the head node process)."""

    def __init__(self, plane: GlobalControlPlane, host: str = "0.0.0.0",
                 port: int = 0):
        self.plane = plane
        self._listener = P.listen_tcp(host, port)
        self.port = self._listener.getsockname()[1]
        self._conns: Dict[int, P.Connection] = {}
        self._conn_node: Dict[int, NodeID] = {}      # node conns, for death
        self._subs: Dict[str, set] = {}              # channel -> conn keys
        self._hooked: set = set()                    # channels with fanout
        self._lock = locksan.lock("gcs_server.conns")
        self._next_key = 1
        self._stopped = threading.Event()
        for t in (self._accept_loop, self._sweep_loop):
            th = threading.Thread(target=t, daemon=True,
                                  name=f"rtpu-gcs-{t.__name__}")
            th.start()

    # ------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = P.Connection(sock)
            with self._lock:
                key = self._next_key
                self._next_key += 1
                self._conns[key] = conn
            threading.Thread(target=self._serve_conn, args=(key, conn),
                             daemon=True, name="rtpu-gcs-conn").start()

    def _serve_conn(self, key: int, conn: P.Connection) -> None:
        while True:
            # burst receive: a node's coalesced cast stream (heartbeats,
            # ref edges, task events) is served per wakeup, not per frame
            msgs = conn.recv_many()
            if msgs is None:
                self._on_conn_closed(key)
                return
            for op, payload in msgs:
                try:
                    if op == P.GCS_CALL:
                        req_id, method, args, kwargs = payload
                        try:
                            result = self._invoke(key, method, args, kwargs)
                            conn.send((P.INFO_REPLY, (req_id, result)))
                        except Exception as e:  # noqa: BLE001 — unblocks
                            conn.send((P.ERROR_REPLY,
                                       (req_id, ser.to_bytes(e))))
                    elif op == P.GCS_CAST:
                        method, args, kwargs = payload
                        try:
                            self._invoke(key, method, args, kwargs)
                        except Exception:
                            pass
                    elif op == P.GCS_SUBSCRIBE:
                        self._subscribe_conn(key, payload)
                except OSError:
                    self._on_conn_closed(key)
                    return

    def _invoke(self, conn_key: int, method: str, args, kwargs) -> Any:
        if method not in _ALLOWED:
            raise ValueError(f"gcs method not allowed: {method}")
        if method == "register_node":
            # remember which conn owns this node: its death is the node's
            info: NodeInfo = args[0]
            with self._lock:
                self._conn_node[conn_key] = info.node_id
        return getattr(self.plane, method)(*args, **kwargs)

    def _subscribe_conn(self, key: int, channel: str) -> None:
        with self._lock:
            self._subs.setdefault(channel, set()).add(key)
            hook = channel not in self._hooked
            if hook:
                self._hooked.add(channel)
        if hook:
            self.plane.subscribe(
                channel, lambda payload, _c=channel: self._fanout(_c, payload))

    def _fanout(self, channel: str, payload: Any) -> None:
        with self._lock:
            keys = list(self._subs.get(channel, ()))
            conns = [(k, self._conns.get(k)) for k in keys]
        for key, conn in conns:
            if conn is None:
                continue
            try:
                conn.send((P.EVENT, (channel, payload)))
            except OSError:
                self._on_conn_closed(key)

    def _on_conn_closed(self, key: int) -> None:
        with self._lock:
            conn = self._conns.pop(key, None)
            node_id = self._conn_node.pop(key, None)
            for subs in self._subs.values():
                subs.discard(key)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        if node_id is not None and not self._stopped.is_set():
            info = self.plane.get_node(node_id)
            if info is not None and info.alive:
                self.plane.remove_node(node_id, reason="gcs connection lost")

    # ------------------------------------------------- failure detection
    def _sweep_loop(self) -> None:
        period = CONFIG.health_check_period_ms / 1000.0
        deadline = period * CONFIG.health_check_failure_threshold
        while not self._stopped.wait(period):
            now = time.monotonic()
            for info in self.plane.alive_nodes():
                if now - info.last_heartbeat > deadline:
                    self.plane.remove_node(
                        info.node_id,
                        reason=f"no heartbeat for {deadline:.0f}s")

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass


class RemoteControlPlane:
    """GlobalControlPlane duck-type over a TCP connection to GcsServer.

    Synchronous methods RPC through one ordered channel, so a cast
    (fire-and-forget mutator) followed by a call is observed in order by
    the server. ``alive_nodes`` is cached briefly: the scheduler calls it
    per task submission and per-task RTTs to the GCS would dominate.
    """

    _CASTS = frozenset({
        "heartbeat", "publish_location", "drop_location",
        "record_task_event", "publish", "kv_del", "finish_job",
        "ref_register", "ref_drop", "drop_all_refs", "pin_task_args",
        "unpin_task_args", "pin_contained", "record_lineage",
        "record_provenance",
        "record_cluster_event", "record_spans", "record_metrics",
        "gen_update", "gen_done", "gen_consumed", "gen_drop",
        "register_pending_pg", "clear_pending_pg",
    })

    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self._conn = P.connect_tcp(host, int(port))
        self._subscribers: Dict[str, List[Callable[[Any], None]]] = {}
        self._sub_lock = locksan.lock("gcs_client.subs")
        self._rpc = RpcChannel(self._conn, on_push=self._on_push)
        self._nodes_cache: Optional[List[NodeInfo]] = None
        self._nodes_cache_at = 0.0
        self._nodes_cache_ttl = CONFIG.health_check_period_ms / 1000.0 / 3

    @property
    def closed(self) -> bool:
        return self._rpc.closed

    def _on_push(self, op: int, payload: Any) -> None:
        if op != P.EVENT:
            return
        channel, data = payload
        if channel == "NODE":
            # membership changed; next alive_nodes() refetches
            self._nodes_cache = None
        with self._sub_lock:
            subs = list(self._subscribers.get(channel, ()))
        for cb in subs:
            try:
                cb(data)
            except Exception:
                pass

    def _call(self, method: str, *args, **kwargs) -> Any:
        from . import telemetry
        t0 = time.monotonic()
        try:
            return self._rpc.request(
                P.GCS_CALL, lambda rid: (rid, method, args, kwargs))
        finally:
            telemetry.counter_inc(telemetry.M_GCS_RPC_TOTAL,
                                  tags=(("kind", "call"),
                                        ("method", method)))
            telemetry.hist_observe(telemetry.M_GCS_RPC_LATENCY,
                                   time.monotonic() - t0,
                                   tags=(("method", method),))

    def _cast(self, method: str, *args, **kwargs) -> None:
        from . import telemetry
        if method != "record_metrics":     # the flush frame itself
            telemetry.counter_inc(telemetry.M_GCS_RPC_TOTAL,
                                  tags=(("kind", "cast"),
                                        ("method", method)))
        self._rpc.send(P.GCS_CAST, (method, args, kwargs))

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in _ALLOWED:
            caller = self._cast if name in self._CASTS else self._call
            return lambda *a, **kw: caller(name, *a, **kw)
        raise AttributeError(name)

    # cached: called by the scheduler on every submission
    def alive_nodes(self) -> List[NodeInfo]:
        now = time.monotonic()
        cached = self._nodes_cache
        if cached is not None and now - self._nodes_cache_at < self._nodes_cache_ttl:
            return cached
        nodes = self._call("alive_nodes")
        self._nodes_cache = nodes
        self._nodes_cache_at = now
        return nodes

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        with self._sub_lock:
            first = channel not in self._subscribers
            self._subscribers.setdefault(channel, []).append(callback)
        if first:
            self._rpc.send(P.GCS_SUBSCRIBE, channel)

    def close(self) -> None:
        self._rpc.close()
