"""Guarded-by field sanitizer: runtime checking of declared data ownership.

The successor layer to ``locksan``: locksan declares every *lock* and
checks acquisition order; this module declares what **data** each lock
protects (``locksan.FIELDS`` — the single Python source of truth behind
DESIGN.md's "Shared-state ownership map", cross-checked both directions
by ``scripts/check_concurrency.py`` rule (h)) and checks, at runtime,
that threads actually follow those declarations. Reference analogue:
Clang ``GUARDED_BY`` thread-safety annotations on ``absl::Mutex``-held
members throughout the C++ core (``src/ray/common/``) — a Python
runtime gets the equivalent from this module (dynamic) plus the AST
pass (static).

Three guard classes, by ``FIELDS`` value:

- ``"<lock name>"`` (a ``locksan.REGISTRY`` row): the field is guarded
  by that lock. Accesses through the instrumentation record
  ``(thread, read|write, guard-held?)``; a cross-thread **read-write or
  write-write pair whose write side did not hold the guard** is
  reported with both sides' stacks. Unguarded *reads* beside guarded
  writes stay silent — single reads are GIL-atomic and several hot
  paths deliberately probe lock-free (e.g. ``gcs.sweep_ref_zeros``);
  the race class that corrupts state in Python is the unguarded
  *write*, and that is what trips the report. For throughput, guarded
  reads are noted 1-in-8 and a *clean* guarded write whose record
  already exists short-cuts to an O(1) held-name probe (the
  ``fieldsan_ab`` gate pins the instrumented path < 1.25x) — an
  UNGUARDED access never takes a short-cut.
- ``"thread:<pat>"``: single-thread-confined — only threads whose name
  contains ``<pat>`` may WRITE (e.g. ``thread:rtpu-dispatch`` for the
  node dispatcher's scheduling state). Reads from other threads are
  tolerated dirty reads by design (the sampler reading queue lengths).
  A write from a foreign thread is reported immediately.
- ``"<lock name>|static"``: guarded by that lock and fully verified by
  the STATIC rule-(h) pass, but exempt from runtime instrumentation —
  the documented hot-path form (per-message transport innards, metric
  shards, per-submission client buffers) where a per-access hook costs
  more than the residual risk of the small audited module it guards.
- ``"atomic:<reason>"``: deliberately lock-free shared state relying on
  GIL-atomic single operations (a counters dict, a write-once flag, an
  idempotent cache fill). Declared so the rule-(h) inference pass can't
  flag it as an *undeclared* shared field; not instrumented.

With ``RTPU_FIELDSAN`` unset/0 everything here is inert: ``guarded``
returns the class unchanged and ``instrument_module`` is a no-op, so a
declaration costs nothing (bench_telemetry's ``fieldsan_ab`` gate pins
the off path at parity). With ``RTPU_FIELDSAN=1`` (tier-1 sets this in
conftest beside RTPU_LOCKSAN) declared instance fields become data
descriptors and declared containers are wrapped in mutation-checking
proxy subclasses (dict/list/set/deque/OrderedDict), so plain attribute
code keeps working unchanged.

Violations go to ``violations()`` and stderr
(``RTPU_FIELDSAN_MODE=log``, the default) or raise
``FieldRaceViolation`` **before the write applies** in ``raise`` mode
(``RTPU_FIELDSAN_MODE=raise`` / ``set_mode("raise")``) — the seeded
two-thread race test demonstrates the access being refused with both
threads surviving. Stack capture on clean (guard-held) accesses is
sampled 1-in-``RTPU_FIELDSAN_SAMPLE`` (default 16) to keep the
instrumented hot path inside the fieldsan_ab budget; unguarded accesses
— the interesting side of any pair — always capture.
"""

from __future__ import annotations

import sys
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from . import locksan
from .config import CONFIG

__all__ = [
    "guarded", "instrument_module", "enabled", "set_mode", "violations",
    "clear_violations", "FieldRaceViolation", "construction",
]

# read once at import: descriptors install at class-creation time, so
# these are environment knobs (RTPU_FIELDSAN / RTPU_FIELDSAN_MODE /
# RTPU_FIELDSAN_SAMPLE via the CONFIG table), not live toggles
_ENABLED = bool(CONFIG.fieldsan)
_MODE = str(CONFIG.fieldsan_mode)
_SAMPLE = max(1, int(CONFIG.fieldsan_sample))

_LOCK, _THREAD, _ATOMIC = 0, 1, 2

_tls = threading.local()

# (owner id, attr) -> (thread id, kind, guard_held, ctx). Plain dict
# with GIL-atomic single ops — this IS the sanitizer, it can't take
# runtime locks. Thread NAMES are resolved only at report time (a
# current_thread() per access was a third of the instrumented-path
# cost). Capped: pathological object churn clears the pairing table
# rather than growing it (one lost pairing window).
_last: Dict[tuple, tuple] = {}
_LAST_CAP = 200_000

_violations: List[dict] = []
_reported: set = set()
_sample_tick = 0
# guarded READS are noted 1-in-N (writes always): a read only matters
# as the pairing partner of an unguarded write, and persistent access
# patterns survive sampling; the refusal semantics live on writes
_read_tick = 0
_READ_SAMPLE = 8


class FieldRaceViolation(RuntimeError):
    """Raised at the access site in ``raise`` mode, BEFORE a write
    applies (the access is refused; both threads survive)."""


def enabled() -> bool:
    return _ENABLED


def set_mode(mode: str) -> str:
    """``log`` (default) or ``raise``; returns the previous mode."""
    global _MODE
    prev, _MODE = _MODE, mode
    return prev


def violations() -> List[dict]:
    return list(_violations)


def clear_violations() -> None:
    _violations.clear()
    _reported.clear()
    _last.clear()


def _init_ids() -> set:
    ids = getattr(_tls, "init_ids", None)
    if ids is None:
        ids = _tls.init_ids = set()
    return ids


class construction:
    """Mark ``obj`` as still under single-threaded construction on this
    thread: accesses to its declared fields are exempt (happens-before
    via the eventual publication). ``guarded`` wraps ``__init__`` in
    this automatically; use it explicitly for post-``__init__`` setup
    that still runs before the object is shared (``NodeService.start``
    hands scheduling state to its freshly-spawned threads)."""

    __slots__ = ("_id", "_mine")

    def __init__(self, obj: Any):
        self._id = id(obj)

    def __enter__(self):
        ids = _init_ids()
        self._mine = self._id not in ids
        if self._mine:
            ids.add(self._id)
        return self

    def __exit__(self, *exc):
        if self._mine:
            _init_ids().discard(self._id)
        return False


def _ctx_capture(skip: int = 2, limit: int = 10) -> tuple:
    """Compact stack: (file, line, func) triples, cheapest to capture
    (no formatting, no frame retention — a retained frame would pin its
    locals for the record's lifetime)."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    out = []
    while f is not None and len(out) < limit:
        co = f.f_code
        if not co.co_filename.endswith("fieldsan.py"):
            out.append((co.co_filename, f.f_lineno, co.co_name))
        f = f.f_back
    return tuple(out)


def _fmt_ctx(ctx: Optional[tuple]) -> str:
    if not ctx:
        return "  (stack not sampled)"
    return "\n".join(f"  {fn}:{ln} in {name}" for fn, ln, name in ctx)


def _report(kind: str, field: str, message: str,
            cur_ctx: tuple, other_ctx: Optional[tuple],
            other_thread: Optional[str]) -> None:
    site = cur_ctx[0] if cur_ctx else None
    rec = {"kind": kind, "field": field, "message": message,
           "thread": threading.current_thread().name,
           "other_thread": other_thread,
           "stack": cur_ctx, "other_stack": other_ctx}
    _violations.append(rec)
    dedup = (kind, field, site)
    if dedup not in _reported:
        _reported.add(dedup)
        other = ("" if other_ctx is None and other_thread is None else
                 f"--- other side (thread {other_thread}) ---\n"
                 f"{_fmt_ctx(other_ctx)}\n")
        print(f"[fieldsan] {kind}: {message} "
              f"(thread {rec['thread']})\n{_fmt_ctx(cur_ctx)}\n{other}",
              file=sys.stderr)
    if _MODE == "raise":
        raise FieldRaceViolation(f"{kind}: {message}")


class _Guard:
    """Parsed FIELDS value."""

    __slots__ = ("kind", "name", "field")

    def __init__(self, field: str, spec: str):
        self.field = field
        if spec.startswith("thread:"):
            self.kind = _THREAD
            self.name = spec[len("thread:"):]
        elif spec.startswith("atomic:"):
            self.kind = _ATOMIC
            self.name = spec[len("atomic:"):]
        elif spec.endswith("|static"):
            # statically verified only (rule (h) checks every lexical
            # write): the documented hot-path exemption for per-message
            # transport/metric-shard innards, where a per-access
            # descriptor hook costs more than the residual risk of the
            # small, audited module it guards
            self.kind = _ATOMIC
            self.name = spec[:-len("|static")]
        else:
            self.kind = _LOCK
            self.name = spec


def _thread_name(tid: Optional[int]) -> str:
    """Best-effort id -> name, resolved only at report time."""
    if tid is None:
        return "?"
    th = threading._active.get(tid)       # noqa: SLF001 — report path
    return th.name if th is not None else f"tid:{tid}"


_lk_tls = locksan._tls


def _note(guard: _Guard, key: tuple, kind: str) -> bool:
    """One access to a declared field. May raise in ``raise`` mode —
    callers invoke it BEFORE applying a write. Returns True when the
    access was clean AND guard-held/owner-matched (callers may memoize
    a clean verdict behind their own held-name probe)."""
    global _sample_tick
    if guard.kind == _THREAD:
        if kind != "w":
            # write confinement only; reads (sampled through the
            # container proxies) are tolerated dirty reads
            return True
        # Per-thread memo of the name match: current_thread() per write
        # was measurable on the dispatcher's inner loop
        memo = getattr(_tls, "owner_ok", None)
        if memo is None:
            memo = _tls.owner_ok = {}
        ok = memo.get(guard.name)
        if ok is None:
            ok = memo[guard.name] = (
                guard.name in threading.current_thread().name)
        if not ok:
            ctx = _ctx_capture()
            _report("confined-write",
                    guard.field,
                    f"write to {guard.field!r} from thread "
                    f"{threading.current_thread().name!r} — declared "
                    f"{guard.name!r}-confined",
                    ctx, None, None)
        return ok
    names = getattr(_lk_tls, "held_names", None)
    ok = names is not None and guard.name in names
    rec = _last.get(key)
    if (rec is not None and ok and rec[1] == kind and rec[2]):
        # CLEAN access repeating the stored clean shape (any thread):
        # the record already carries everything a future unguarded
        # access needs to pair against — skip the re-record. This is
        # the hot-path common case, and under the n_n bench's
        # 8-driver-thread contention it is what keeps clean traffic
        # O(1) allocation-free. (Unguarded accesses never short-cut.)
        return ok
    tid = threading.get_ident()
    _sample_tick += 1
    ctx = (_ctx_capture() if (not ok or _sample_tick % _SAMPLE == 0)
           else None)
    if (rec is not None and rec[0] != tid
            and (kind == "w" or rec[1] == "w")
            and ((kind == "w" and not ok) or (rec[1] == "w" and not rec[2]))):
        what = ("write-write" if kind == "w" and rec[1] == "w"
                else "read-write")
        other_name = _thread_name(rec[0])
        side = "this write" if (kind == "w" and not ok) else \
            f"the {('write' if rec[1] == 'w' else 'read')} on " \
            f"thread {other_name!r}"
        if ctx is None:
            ctx = _ctx_capture()
        # raise mode propagates from _report BEFORE the record below:
        # a REFUSED write never applied, so it must not become the
        # "last access" later readers pair against
        _report("race", guard.field,
                f"{what} race on {guard.field!r}: accessed by two "
                f"threads with {side} not holding declared guard "
                f"{guard.name!r}",
                ctx, rec[3], other_name)
        _last[key] = (tid, kind, ok, ctx)
        return False
    if len(_last) > _LAST_CAP:
        _last.clear()
    _last[key] = (tid, kind, ok, ctx)
    return ok


# ------------------------------------------------------------- proxies
#
# Container subclasses that route mutations (and, for module-level
# fields, the common reads) through ``_note``. They pickle/copy as the
# PLAIN base type (a proxy must never cross a process boundary), and
# ``dict.copy()``-style methods already return base types in CPython.

def _in_init(owner_id: int) -> bool:
    """Is ``owner_id`` inside THIS thread's construction window? A
    purely thread-local probe — construction exemptions never cross
    threads, so there is no shared counter (a shared fast-path counter
    was a lost-update race under concurrent constructions)."""
    ids = getattr(_tls, "init_ids", None)
    return ids is not None and owner_id in ids


def _p_note(proxy, kind: str) -> None:
    spec = proxy._fs_spec
    if spec is None:
        return
    guard, key = spec
    if kind == "w":
        # clean-verdict memo — the hot-path fast exit that holds the
        # instrumented path inside the fieldsan_ab budget. Thread-
        # confined: the owning thread's verdict never changes, memo is
        # its id. Lock-guarded: once ONE clean write is recorded in
        # _last (memo=True), a further write while the guard is HELD
        # adds no pairing information — the only accesses that matter
        # are unguarded ones, and they fail the held probe and take
        # the full path.
        memo = proxy._fs_memo
        if memo is not None:
            if guard.kind == _THREAD:
                if memo == threading.get_ident():
                    return
            else:
                names = getattr(_lk_tls, "held_names", None)
                if names is not None and guard.name in names:
                    return
    if _in_init(key[0]):
        return
    ok = _note(guard, key, kind)
    if ok and kind == "w":
        proxy._fs_memo = (threading.get_ident()
                          if guard.kind == _THREAD else True)


def _p_note_r(proxy) -> None:
    """Sampled read note for proxy read methods (1-in-_READ_SAMPLE)."""
    global _read_tick
    _read_tick += 1
    if _read_tick % _READ_SAMPLE:
        return
    spec = proxy._fs_spec
    if spec is None or _in_init(spec[1][0]):
        return
    _note(spec[0], spec[1], "r")


class _GDict(dict):
    __slots__ = ("_fs_spec", "_fs_memo")

    def __reduce_ex__(self, protocol):
        return (dict, (dict(self),))

    def __setitem__(self, k, v):
        _p_note(self, "w")
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        _p_note(self, "w")
        dict.__delitem__(self, k)

    def __getitem__(self, k):
        _p_note_r(self)
        return dict.__getitem__(self, k)

    def get(self, k, default=None):
        _p_note_r(self)
        return dict.get(self, k, default)

    def pop(self, *a):
        _p_note(self, "w")
        return dict.pop(self, *a)

    def popitem(self):
        _p_note(self, "w")
        return dict.popitem(self)

    def clear(self):
        _p_note(self, "w")
        dict.clear(self)

    def update(self, *a, **k):
        _p_note(self, "w")
        dict.update(self, *a, **k)

    def setdefault(self, k, default=None):
        _p_note(self, "w")
        return dict.setdefault(self, k, default)


class _GODict(OrderedDict):
    __slots__ = ("_fs_spec", "_fs_memo")

    def __reduce_ex__(self, protocol):
        return (OrderedDict, (list(self.items()),))

    def __setitem__(self, k, v):
        _p_note(self, "w")
        OrderedDict.__setitem__(self, k, v)

    def __delitem__(self, k):
        _p_note(self, "w")
        OrderedDict.__delitem__(self, k)

    def pop(self, *a):
        _p_note(self, "w")
        return OrderedDict.pop(self, *a)

    def popitem(self, last=True):
        _p_note(self, "w")
        return OrderedDict.popitem(self, last)

    def clear(self):
        _p_note(self, "w")
        OrderedDict.clear(self)

    def update(self, *a, **k):
        _p_note(self, "w")
        OrderedDict.update(self, *a, **k)

    def setdefault(self, k, default=None):
        _p_note(self, "w")
        return OrderedDict.setdefault(self, k, default)

    def move_to_end(self, k, last=True):
        _p_note(self, "w")
        OrderedDict.move_to_end(self, k, last)


class _GList(list):
    __slots__ = ("_fs_spec", "_fs_memo")

    def __reduce_ex__(self, protocol):
        return (list, (list(self),))

    def __setitem__(self, i, v):
        _p_note(self, "w")
        list.__setitem__(self, i, v)

    def __delitem__(self, i):
        _p_note(self, "w")
        list.__delitem__(self, i)

    def __iadd__(self, other):
        _p_note(self, "w")
        list.extend(self, other)
        return self

    def append(self, v):
        _p_note(self, "w")
        list.append(self, v)

    def extend(self, it):
        _p_note(self, "w")
        list.extend(self, it)

    def insert(self, i, v):
        _p_note(self, "w")
        list.insert(self, i, v)

    def remove(self, v):
        _p_note(self, "w")
        list.remove(self, v)

    def pop(self, *a):
        _p_note(self, "w")
        return list.pop(self, *a)

    def clear(self):
        _p_note(self, "w")
        list.clear(self)

    def sort(self, **k):
        _p_note(self, "w")
        list.sort(self, **k)

    def reverse(self):
        _p_note(self, "w")
        list.reverse(self)


class _GSet(set):
    __slots__ = ("_fs_spec", "_fs_memo")

    def __reduce_ex__(self, protocol):
        return (set, (set(self),))

    def add(self, v):
        _p_note(self, "w")
        set.add(self, v)

    def discard(self, v):
        _p_note(self, "w")
        set.discard(self, v)

    def remove(self, v):
        _p_note(self, "w")
        set.remove(self, v)

    def pop(self):
        _p_note(self, "w")
        return set.pop(self)

    def clear(self):
        _p_note(self, "w")
        set.clear(self)

    def update(self, *a):
        _p_note(self, "w")
        set.update(self, *a)

    def difference_update(self, *a):
        _p_note(self, "w")
        set.difference_update(self, *a)


class _GDeque(deque):
    # deque disallows __slots__ with nonzero instance size on some
    # builds; plain class attribute slots keep it simple
    _fs_spec: Any = None
    _fs_memo: Any = None

    def __reduce_ex__(self, protocol):
        return (deque, (list(self), self.maxlen))

    def __setitem__(self, i, v):
        _p_note(self, "w")
        deque.__setitem__(self, i, v)

    def __delitem__(self, i):
        _p_note(self, "w")
        deque.__delitem__(self, i)

    def append(self, v):
        _p_note(self, "w")
        deque.append(self, v)

    def appendleft(self, v):
        _p_note(self, "w")
        deque.appendleft(self, v)

    def extend(self, it):
        _p_note(self, "w")
        deque.extend(self, it)

    def extendleft(self, it):
        _p_note(self, "w")
        deque.extendleft(self, it)

    def pop(self):
        _p_note(self, "w")
        return deque.pop(self)

    def popleft(self):
        _p_note(self, "w")
        return deque.popleft(self)

    def remove(self, v):
        _p_note(self, "w")
        deque.remove(self, v)

    def clear(self):
        _p_note(self, "w")
        deque.clear(self)

    def rotate(self, n=1):
        _p_note(self, "w")
        deque.rotate(self, n)


# exact-type wrapping only: subclasses (defaultdict, user types) keep
# their behavior and stay uninstrumented beyond the binding itself
_WRAP: Dict[type, type] = {dict: _GDict, OrderedDict: _GODict,
                           list: _GList, set: _GSet, deque: _GDeque}
_PROXIES = (_GDict, _GODict, _GList, _GSet, _GDeque)


def _wrap(value: Any, guard: _Guard, key: tuple) -> Any:
    if isinstance(value, _PROXIES):
        return value
    cls = _WRAP.get(type(value))
    if cls is None:
        return value
    if cls is _GDeque:
        out = (_GDeque(value, value.maxlen) if value.maxlen is not None
               else _GDeque(value))
    elif cls is _GODict:
        out = _GODict(value.items())
    else:
        out = cls(value)
    out._fs_spec = (guard, key)
    out._fs_memo = None
    return out


# ---------------------------------------------------------- descriptor

class _GuardedField:
    """Data descriptor over a declared instance field. Values live in
    the instance ``__dict__`` under the plain attribute name (or the
    wrapped ``__slots__`` descriptor), so pickling / ``vars()`` /
    dataclass-style code see ordinary state."""

    __slots__ = ("attr", "guard", "inner", "memo_key")

    def __init__(self, attr: str, guard: _Guard, inner: Any = None):
        self.attr = attr
        self.guard = guard
        self.inner = inner
        self.memo_key = "_fs_memo#" + attr

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if self.inner is not None:
            val = self.inner.__get__(obj, objtype)
        else:
            try:
                val = obj.__dict__[self.attr]
            except KeyError:
                raise AttributeError(self.attr) from None
        if self.guard.kind == _LOCK:
            global _read_tick
            _read_tick += 1
            if not _read_tick % _READ_SAMPLE and not _in_init(id(obj)):
                _note(self.guard, (id(obj), self.attr), "r")
        return val

    def __set__(self, obj, value):
        key = (id(obj), self.attr)
        if self.inner is None and obj.__dict__.get(self.memo_key):
            # clean-verdict memo (see _p_note): once a clean write is
            # recorded, a guarded rebind adds no pairing information
            names = getattr(_lk_tls, "held_names", None)
            if names is not None and self.guard.name in names:
                obj.__dict__[self.attr] = _wrap(value, self.guard, key)
                return
        if not _in_init(key[0]):
            ok = _note(self.guard, key, "w")   # raise mode refuses here
            if ok and self.inner is None:
                obj.__dict__[self.memo_key] = True
        value = _wrap(value, self.guard, key)
        if self.inner is not None:
            self.inner.__set__(obj, value)
        else:
            obj.__dict__[self.attr] = value

    def __delete__(self, obj):
        key = (id(obj), self.attr)
        if not _in_init(key[0]):
            _note(self.guard, key, "w")
        if self.inner is not None:
            self.inner.__delete__(obj)
        else:
            del obj.__dict__[self.attr]

    def __repr__(self):
        return f"<GuardedField {self.guard.field!r}>"


class _WriteGuardedField:
    """Write-only data descriptor for thread-confined fields backed by
    the instance ``__dict__``: defining ``__set__`` without ``__get__``
    lets CPython serve READS straight from the instance dict at native
    speed (confined reads are unchecked dirty reads by design), while
    every write still routes through the confinement check."""

    __slots__ = ("attr", "guard", "memo_key")

    def __init__(self, attr: str, guard: _Guard):
        self.attr = attr
        self.guard = guard
        self.memo_key = "_fs_memo#" + attr

    def __set__(self, obj, value):
        key = (id(obj), self.attr)
        if obj.__dict__.get(self.memo_key) == threading.get_ident():
            obj.__dict__[self.attr] = _wrap(value, self.guard, key)
            return
        if not _in_init(key[0]):
            if _note(self.guard, key, "w"):
                obj.__dict__[self.memo_key] = threading.get_ident()
        obj.__dict__[self.attr] = _wrap(value, self.guard, key)

    def __delete__(self, obj):
        key = (id(obj), self.attr)
        if not _in_init(key[0]):
            _note(self.guard, key, "w")
        del obj.__dict__[self.attr]

    def __repr__(self):
        return f"<WriteGuardedField {self.guard.field!r}>"


def _class_fields(prefix: str) -> Dict[str, str]:
    plen = len(prefix) + 1
    return {key[plen:]: spec for key, spec in locksan.FIELDS.items()
            if key.startswith(prefix + ".") and "." not in key[plen:]}


def guarded(cls: type) -> type:
    """Class decorator installing fieldsan instrumentation for every
    ``locksan.FIELDS`` row declared under ``<module short name>.<class
    name>.<attr>``. A pure pass-through when RTPU_FIELDSAN is off —
    declaring ownership costs nothing in production."""
    if not _ENABLED:
        return cls
    prefix = cls.__module__.rsplit(".", 1)[-1] + "." + cls.__name__
    fields = _class_fields(prefix)
    installed = False
    for attr, spec in fields.items():
        guard = _Guard(f"{prefix}.{attr}", spec)
        if guard.kind == _ATOMIC:
            continue
        inner = cls.__dict__.get(attr)
        if inner is not None and not (hasattr(inner, "__get__")
                                      and hasattr(inner, "__set__")):
            inner = None            # plain class default, not a slot
        if guard.kind == _THREAD and inner is None:
            setattr(cls, attr, _WriteGuardedField(attr, guard))
        else:
            setattr(cls, attr, _GuardedField(attr, guard, inner))
        installed = True
    if installed:
        orig_init = cls.__init__

        def __init__(self, *a, _fs_orig=orig_init, **k):
            with construction(self):
                _fs_orig(self, *a, **k)

        __init__.__wrapped__ = orig_init
        cls.__init__ = __init__
    return cls


def instrument_module(namespace: Dict[str, Any], modshort: str) -> None:
    """Wrap a module's declared module-level containers (two-part
    FIELDS keys, ``"<modshort>.<name>"``) in checking proxies. Call at
    the bottom of the module. No-op when fieldsan is off."""
    if not _ENABLED:
        return
    for key, spec in locksan.FIELDS.items():
        parts = key.split(".")
        if len(parts) != 2 or parts[0] != modshort:
            continue
        guard = _Guard(key, spec)
        if guard.kind == _ATOMIC:
            continue
        attr = parts[1]
        val = namespace.get(attr)
        if val is None:
            continue
        namespace[attr] = _wrap(val, guard, (modshort, attr))
