"""Binary identifiers for jobs, tasks, actors, objects, nodes and workers.

Equivalent role to the reference's 128/160-bit binary IDs
(``src/ray/common/id.h``): stable, hashable, cheaply serializable IDs that
embed lineage information (an ObjectID embeds the TaskID that created it,
a TaskID embeds its JobID).  We use 16-byte random IDs with small structured
prefixes rather than the reference's exact layouts — the layout is our own.
"""

from __future__ import annotations

import os
import threading

_ID_SIZE = 16

_local = threading.local()


_POOL_REFILL = 256 * _ID_SIZE


def _random_bytes(n: int = _ID_SIZE) -> bytes:
    """Entropy from a thread-local urandom pool: one syscall buys 256
    ids (a per-task urandom() call was ~13% of submission CPU in the
    core microbench)."""
    buf = getattr(_local, "pool", b"")
    if len(buf) < n:
        buf = os.urandom(max(_POOL_REFILL, n))
    _local.pool = buf[n:]
    return buf[:n]


# a forked child must never replay the parent's pooled entropy
# (workers here are spawned, not forked — this is belt-and-braces)
os.register_at_fork(after_in_child=lambda: setattr(_local, "pool", b""))


class BaseID:
    """Immutable binary ID. Subclasses differ only by kind tag."""

    __slots__ = ("_bytes", "_hash")

    KIND = b"\x00"

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != _ID_SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {_ID_SIZE} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    @classmethod
    def from_random(cls):
        return cls(cls.KIND + _random_bytes(_ID_SIZE - 1))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * _ID_SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * _ID_SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return isinstance(other, BaseID) and other._bytes == self._bytes

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    KIND = b"\x01"


class NodeID(BaseID):
    KIND = b"\x02"


class WorkerID(BaseID):
    KIND = b"\x03"


class TaskID(BaseID):
    KIND = b"\x04"

    @classmethod
    def for_job(cls, job_id: JobID):
        """Derive a fresh task id carrying the job id in its suffix."""
        return cls(cls.KIND + _random_bytes(_ID_SIZE - 5) + job_id.binary()[1:5])


class ActorID(BaseID):
    KIND = b"\x05"


class ObjectID(BaseID):
    """Object ids embed the creating task's entropy so lineage can be traced.

    Reference analogue: ObjectID = TaskID + return-index
    (``src/ray/common/id.h`` ObjectID::FromIndex).
    """

    KIND = b"\x06"

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int):
        """Layout: task-id entropy bytes [1:16) + 1-byte return index, so the
        full creating TaskID is recoverable (see ObjectRef.task_id)."""
        if index < 0 or index > 0xFF:
            raise ValueError(f"return index out of range: {index}")
        return cls(task_id.binary()[1:16] + index.to_bytes(1, "big"))

    @classmethod
    def for_gen_item(cls, task_id: "TaskID", index: int):
        """Dynamic (streaming) return item ids. Layout: task-id entropy
        [1:11) + 0xFE marker + u32 index — a streaming task can yield up
        to 2**32 items (reference: ObjectRefGenerator's dynamically
        allocated return ids, ``_raylet.pyx:252``)."""
        if index < 0 or index > 0xFFFFFFFF:
            raise ValueError(f"generator item index out of range: {index}")
        return cls(task_id.binary()[1:12] + b"\xfe"
                   + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, owner: WorkerID):
        """Layout: KIND + 7 owner-entropy bytes + 8 random, so the owning
        worker is identifiable from the id during debugging/recovery."""
        return cls(cls.KIND + owner.binary()[1:8]
                   + _random_bytes(_ID_SIZE - 8))

    def task_entropy(self) -> bytes:
        return self._bytes[:15]

    def return_index(self) -> int:
        return self._bytes[15]


class PlacementGroupID(BaseID):
    KIND = b"\x07"
