"""Shared-memory object store (plasma-equivalent).

Equivalent role to the reference's plasma store
(``src/ray/object_manager/plasma/store.h:55`` — shm segments + allocator +
LRU eviction + spilling). Design differences, on purpose:

- Objects are immutable, one POSIX shm segment per large object
  (``multiprocessing.shared_memory``) instead of one dlmalloc arena — the
  kernel is our allocator; small objects are carried inline in RPC frames
  (reference analogue: in-memory store, ``memory_store.h:43``).
- Readers in any process attach by name for zero-copy access (pickle-5
  out-of-band buffers point straight into the mapping), standing in for
  plasma's fd-passing (``fling.cc``).
- When the store exceeds its budget, least-recently-used unpinned primary
  copies are spilled to disk files and restored on demand (reference
  analogue: ``local_object_manager.h:110`` + ``external_storage.py:246``).
"""

from __future__ import annotations

import glob
import itertools
import json
import os
import pickle
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import shared_memory, resource_tracker
from typing import Dict, List, Optional, Tuple

from . import fieldsan
from . import locksan
from . import serialization as ser
from .config import CONFIG
from .ids import ObjectID

_SHM_PREFIX = "rtpu"
_SHM_DIR = "/dev/shm"

# Secondary-copy (adopted) segments get a per-call unique suffix: two
# concurrent pulls of the same object in one process must not collide on
# a deterministic name (the second create would raise FileExistsError and
# fail the pull instead of deduping).
_adopt_seq = itertools.count()


def _adopt_segment_name(object_id: ObjectID) -> str:
    return (f"{_segment_name(object_id)}p{os.getpid() % 100000}"
            f"c{next(_adopt_seq)}")


def _mk_meta(t: tuple) -> "ObjectMeta":
    """Rebuild an ObjectMeta from its flattened wire tuple (see
    ``ObjectMeta.__reduce__``)."""
    m = ObjectMeta.__new__(ObjectMeta)
    (oid, m.size, m.inline, m.shm_name, m.error, m.node_hint,
     m.arena_ref, m.flags) = t
    m.object_id = ObjectID(oid)
    return m


def _proc_start_token(pid: int) -> Optional[str]:
    """Process identity token: the kernel start time (field 22 of
    ``/proc/<pid>/stat``, in jiffies). A (pid, starttime) pair uniquely
    names one process incarnation, so a recycled pid can't masquerade as
    a live manifest owner. None off-Linux (reaping degrades to never)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        # comm (field 2) may contain spaces/parens; fields resume after
        # the LAST ')' — starttime is the 20th field from there
        return stat[stat.rindex(b")") + 2:].split()[19].decode()
    except (OSError, ValueError, IndexError):
        return None


def reap_orphan_shm(root: str = _SHM_DIR) -> int:
    """Unlink shm artifacts (arena file + segments) left by stores whose
    owner process died without ``shutdown()`` (SIGKILL, OOM-kill). Every
    store appends what it creates to a small manifest file in /dev/shm
    keyed by (pid, starttime); this scans all manifests, skips live
    owners, and removes everything a dead owner left behind — reference
    analogue: the raylet's plasma directory cleanup on restart. Called
    from every store __init__ (so the next node to start on the host
    collects the garbage) and from ``rtpu`` CLI paths. Returns the
    number of artifacts removed."""
    reaped = 0
    for mf in glob.glob(os.path.join(root, "rtpu_manifest_*")):
        try:
            with open(mf, "r") as f:
                lines = f.read().splitlines()
            hdr = json.loads(lines[0])
        except (OSError, ValueError, IndexError):
            continue
        pid = hdr.get("pid")
        if pid and _proc_start_token(pid) == hdr.get("start"):
            continue                      # owner incarnation still alive
        for name in [hdr.get("arena")] + lines[1:]:
            if not name:
                continue
            path = name if os.path.isabs(name) else os.path.join(root, name)
            try:
                os.unlink(path)
                reaped += 1
            except OSError:
                pass
        try:
            os.unlink(mf)
        except OSError:
            pass
    return reaped


def _segment_name(object_id: ObjectID) -> str:
    # Full 32-hex-char id: put ids carry only 8 random bytes (the rest is
    # owner entropy), so truncating here would leave too little entropy
    # and collide segment names at scale.
    return f"{_SHM_PREFIX}{object_id.hex()}"


def create_segment(object_id: ObjectID, size: int) -> shared_memory.SharedMemory:
    """Create a named segment from a non-authority process (worker/driver
    writing a large object directly). Unregistered from the resource tracker
    because lifetime is owned by the node store that adopts it."""
    seg = shared_memory.SharedMemory(
        create=True, size=max(size, 1), name=_segment_name(object_id))
    try:
        resource_tracker.unregister(seg._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    return seg


class _AttachedSegment(shared_memory.SharedMemory):
    """Reader-side attachment. Swallows the BufferError raised at interpreter
    exit when user code still holds zero-copy numpy views into the mapping
    (the OS reclaims it anyway)."""

    def __del__(self):
        try:
            super().__del__()
        except BufferError:
            pass


def attach_segment(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment. Python 3.12's SharedMemory registers
    with the resource tracker only on create, so attaching needs no
    unregister dance; cleanup is owned by the node store."""
    return _AttachedSegment(name=name)


@dataclass
class ObjectMeta:
    """Where an object's value lives; travels in RPC messages."""

    # flag bits (``flags``):
    # LAZY — the primary's bytes still live in the owner process's heap,
    # promoted to shm on first cross-process demand (reference analogue:
    # CoreWorker in-memory store → plasma promotion). SPILLED — the
    # primary lives in a spill file on the owner's disk; directory rows
    # sharing this meta thereby advertise the spilled location
    # (restore-on-get clears it).
    LAZY = 1
    SPILLED = 2

    object_id: ObjectID
    size: int
    inline: Optional[bytes] = None  # wire-format bytes, for small objects
    shm_name: Optional[str] = None  # segment name, for large objects
    error: Optional[bytes] = None   # pickled exception, for failed tasks
    node_hint: Optional[bytes] = None  # NodeID binary of a known location
    # (arena_path, payload_offset): object lives in the node's C++ shm
    # arena (plasma-style Create/Seal; ``native/object_arena.cpp``)
    arena_ref: Optional[tuple] = None
    flags: int = 0

    def __reduce_ex__(self, protocol):
        # hot-path pickle: metas ride every TASK_DONE / GET_REPLY /
        # dispatch frame; flat tuple with the id as raw bytes is ~4x
        # cheaper than the default dataclass reduce (see
        # TaskSpec.__reduce__ for the measurement). Large inline
        # payloads wrap in a PickleBuffer so the transport ships them
        # out-of-band as iovecs (zero copy through the pickle stream);
        # a pickler with no buffer_callback keeps them in-band, so
        # non-transport picklings (GCS persistence) still work.
        inline = self.inline
        if inline is not None:
            if (protocol >= 5
                    and len(inline) >= CONFIG.transport_oob_threshold_bytes):
                inline = pickle.PickleBuffer(inline)
            elif not isinstance(inline, bytes):
                # normalize foreign buffer types: a meta re-forwarded
                # after an out-of-band decode carries a memoryview,
                # which plain pickle rejects
                inline = bytes(inline)
        return (_mk_meta, ((self.object_id.binary(), self.size,
                            inline, self.shm_name, self.error,
                            self.node_hint, self.arena_ref, self.flags),))

    def is_error(self) -> bool:
        return self.error is not None

    def has_value(self) -> bool:
        # a LAZY or SPILLED meta has a value — it just isn't mappable
        # right now (resolvable through the owner, like any remote
        # location)
        return (self.inline is not None or self.shm_name is not None
                or self.arena_ref is not None or self.error is not None
                or bool(self.flags & (ObjectMeta.LAZY
                                      | ObjectMeta.SPILLED)))


@dataclass
class _Entry:
    meta: ObjectMeta
    segment: Optional[shared_memory.SharedMemory] = None
    sealed: bool = False
    pinned: int = 0
    spilled_path: Optional[str] = None
    last_used: float = field(default_factory=time.monotonic)
    charged: bool = False  # whether meta.size is counted in store._used
    # meta has been handed to a reader: arena-backed entries then become
    # unspillable — a reader may hold zero-copy views into the arena, and
    # unlike POSIX segments (kernel refcount keeps pages alive) a freed
    # arena block gets reused, which would silently corrupt those views
    ever_read: bool = False
    # connection that holds an unsealed Create; its death reclaims it
    writer_tag: Optional[int] = None
    # lazy primary: (serialized_meta_bytes, out-of-band views) still in
    # this process's heap; promoted by _materialize_locked on demand
    lazy: Optional[tuple] = None


@fieldsan.guarded
class ObjectStore:
    """Node-local authority over object values.

    Thread-safe; used from the node service event loop and (for driver-side
    fast-path puts) the driver thread.
    """

    # arena-eligible payload range: below -> inline, above -> dedicated
    # segment (huge objects would fragment the arena). Class default;
    # scaled to capacity//4 per instance — the arena memcpy path is ~7x
    # faster than first-touch faulting a fresh POSIX segment.
    ARENA_MAX_OBJECT = 64 << 20

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self._lock = locksan.rlock("store.entries")
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()
        self._capacity = (capacity_bytes
                          or CONFIG.object_store_shm_max_bytes
                          or CONFIG.object_store_memory_mb * (1 << 20))
        self.ARENA_MAX_OBJECT = max(64 << 20, self._capacity // 4)
        self._used = 0
        self._spill_dir = spill_dir or CONFIG.object_store_spill_dir or "/tmp/rtpu_spill"
        self.num_spilled = 0
        self.num_restored = 0
        self.num_lazy_puts = 0
        self.num_materialized = 0
        self.spilled_bytes_total = 0
        self.restored_bytes_total = 0
        # ("spill"|"restore", ObjectID, size) tuples appended under _lock
        # and drained by the node service, which emits the attributed
        # OBJECT_SPILLED/OBJECT_RESTORED events + byte counters OUTSIDE
        # the store lock (the store must not call into gcs/telemetry with
        # its lock held — lock-order hygiene)
        self._spill_events: List[tuple] = []
        # collect what crashed predecessors left in /dev/shm before we
        # add our own arena/segments to it
        reap_orphan_shm()
        # C++ shm arena (plasma-equivalent allocator). One mapping per
        # node; all readers attach once. Optional: pure-python segments
        # remain the fallback and the path for huge objects.
        self._arena = None
        # freed-while-read arena blocks: (release_at, offset). A reader's
        # zero-copy numpy views alias arena bytes with no kernel refcount
        # (unlike POSIX segments), so reuse is delayed by
        # CONFIG.arena_free_quarantine_s after an explicit free().
        self._quarantine: List[tuple] = []
        if CONFIG.use_native_arena:
            try:
                from . import native
                if native.available():
                    # random suffix: pid+id can repeat across store
                    # restarts in one process, and reader processes cache
                    # mappings by path
                    suffix = os.urandom(8).hex()
                    path = f"/dev/shm/rtpu_arena_{suffix}"
                    self._arena = native.Arena(path, self._capacity)
            except Exception:
                self._arena = None
        # crash manifest: everything this store parks in /dev/shm is
        # recorded here (header: owner identity + arena path; one line
        # per segment), so reap_orphan_shm() can clean up after a
        # SIGKILL'd node. Flushed per append — durability against
        # SIGKILL is the whole point.
        self._manifest_f = None
        self._manifest_path = None
        try:
            self._manifest_path = os.path.join(
                _SHM_DIR,
                f"rtpu_manifest_{os.getpid()}_{os.urandom(4).hex()}")
            self._manifest_f = open(self._manifest_path, "w")
            self._manifest_f.write(json.dumps({
                "pid": os.getpid(),
                "start": _proc_start_token(os.getpid()),
                "arena": self._arena.path if self._arena else None,
            }) + "\n")
            self._manifest_f.flush()
        except OSError:
            self._manifest_f = None
            self._manifest_path = None

    def _manifest_add(self, name: Optional[str]) -> None:
        """Record a segment this store owns in the crash manifest (the
        file object serializes concurrent appends; each append is one
        short write + flush)."""
        f = self._manifest_f
        if f is None or not name:
            return
        try:
            f.write(name + "\n")
            f.flush()
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------------ put
    def put_inline(self, object_id: ObjectID, data: bytes) -> ObjectMeta:
        if not isinstance(data, bytes):
            # a store-resident inline must own its bytes: a zero-copy
            # view into a transport frame buffer would pin the whole
            # (up to max-batch-sized) frame for the object's lifetime
            data = bytes(data)
        meta = ObjectMeta(object_id=object_id, size=len(data), inline=data)
        with self._lock:
            self._ensure_capacity(len(data))
            self._entries[object_id] = _Entry(meta=meta, sealed=True,
                                              charged=True)
            self._used += len(data)
        return meta

    def put_lazy(self, object_id: ObjectID, smeta: bytes,
                 views: List[memoryview], total: int) -> Optional[ObjectMeta]:
        """Zero-copy put for a SAME-PROCESS writer (the head driver): the
        serialized form — meta pickle + out-of-band views straight into
        the caller's buffers — is parked in-heap and the entry is sealed
        immediately; **no bytes are copied at put time**. Promotion to
        the arena/a segment (the one unavoidable copy) happens on first
        cross-process demand, restore-blocking spill pressure, or pull —
        and never happens for objects freed unread. Reference analogue:
        the CoreWorker's in-memory store, from which objects are promoted
        to plasma only when another process needs them.

        The views alias the caller's object storage, so a caller that
        mutates the source object before the first get can observe its
        own mutation (documented at ``object_store_lazy_put``).
        Returns None when a sealed copy already exists (duplicate put)."""
        meta = ObjectMeta(object_id=object_id, size=total,
                          flags=ObjectMeta.LAZY)
        with self._lock:
            if object_id in self._entries:
                return None
            self._ensure_capacity(total)
            self._entries[object_id] = _Entry(
                meta=meta, sealed=True, charged=True,
                lazy=(smeta, list(views)))
            self._used += total
            self.num_lazy_puts += 1
        return meta

    # concurrency: requires(store.entries)
    def _materialize_locked(self, e: _Entry) -> None:
        """Promote a lazy primary into shared memory (arena block when it
        fits, else an owned segment). Budget was charged at put_lazy time
        so only the physical home changes here."""
        smeta, views = e.lazy
        size = e.meta.size
        off = (self._arena.alloc(size)
               if (self._arena is not None
                   and size <= self.ARENA_MAX_OBJECT) else None)
        if off is not None:
            ser.write_to(self._arena.buffer(off, size), smeta, views)
            e.meta.arena_ref = (self._arena.path, off)
        else:
            seg = shared_memory.SharedMemory(
                create=True, size=max(size, 1),
                name=_segment_name(e.meta.object_id))
            self._manifest_add(seg.name)
            ser.write_to(seg.buf, smeta, views)
            e.segment = seg
            e.meta.shm_name = seg.name
        e.meta.flags &= ~ObjectMeta.LAZY
        e.lazy = None
        self.num_materialized += 1

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        """Allocate a shm segment; caller fills it then calls seal()."""
        with self._lock:
            self._ensure_capacity(size)
            seg = shared_memory.SharedMemory(
                create=True, size=max(size, 1), name=_segment_name(object_id))
            self._manifest_add(seg.name)
            meta = ObjectMeta(object_id=object_id, size=size,
                              shm_name=seg.name)
            self._entries[object_id] = _Entry(meta=meta, segment=seg,
                                              charged=True)
            self._used += size
            return seg.buf[:size]

    def seal(self, object_id: ObjectID) -> ObjectMeta:
        with self._lock:
            entry = self._entries[object_id]
            entry.sealed = True
            entry.last_used = time.monotonic()
            return entry.meta

    def put_error(self, object_id: ObjectID, error: bytes) -> ObjectMeta:
        meta = ObjectMeta(object_id=object_id, size=len(error), error=error)
        with self._lock:
            self._entries[object_id] = _Entry(meta=meta, sealed=True)
        return meta

    def alloc_in_arena(self, object_id: ObjectID, size: int,
                       writer_tag: Optional[int] = None) -> Optional[tuple]:
        """Plasma-style Create: reserve arena space for a writer in
        another process. Returns (arena_path, offset) or None (no arena /
        full / out of the arena size class). The entry exists unsealed
        until the writer's seal (adopt) lands; ``writer_tag`` (the
        writer's connection key) lets ``reclaim_unsealed`` free the block
        if the writer dies before sealing."""
        if self._arena is None or size > self.ARENA_MAX_OBJECT:
            return None
        with self._lock:
            self._sweep_quarantine()
            if object_id in self._entries:
                return None
            self._ensure_capacity(size)
            off = self._arena.alloc(size)
            if off is None:
                return None
            meta = ObjectMeta(object_id=object_id, size=size,
                              arena_ref=(self._arena.path, off))
            self._entries[object_id] = _Entry(meta=meta, charged=True,
                                              writer_tag=writer_tag)
            self._used += size
            return (self._arena.path, off)

    # concurrency: requires(store.entries)
    def _release_unsealed_locked(self, object_id: ObjectID,
                                 e: "_Entry") -> None:
        """Pop an unsealed entry and free its allocation (callers hold
        ``_lock``). The single home for the uncharge/arena-free
        sequence, shared by dead-writer reclaim and stale-Create
        replacement."""
        self._entries.pop(object_id, None)
        if e.charged:
            self._used -= e.meta.size
        if (e.meta.arena_ref is not None and self._arena is not None
                and e.meta.arena_ref[0] == self._arena.path):
            self._arena.free(e.meta.arena_ref[1])

    def reclaim_unsealed(self, writer_tag: int) -> None:
        """Free arena Creates whose writer connection died pre-seal."""
        with self._lock:
            dead = [(oid, e) for oid, e in self._entries.items()
                    if not e.sealed and e.writer_tag == writer_tag]
            for oid, e in dead:
                self._release_unsealed_locked(oid, e)

    def abort_create(self, object_id: ObjectID) -> None:
        """Discard an unsealed Create whose writer failed mid-fill: pop
        the entry, uncharge the budget, and return its allocation (arena
        block or owned shm segment). Without this a failed fill leaves a
        permanently unsealed entry that ``reclaim_unsealed`` can never
        match (no writer_tag) while its bytes stay charged forever."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.sealed:
                return
            self._release_unsealed_locked(object_id, e)
            if e.segment is not None:
                try:
                    e.segment.close()
                except (OSError, BufferError):
                    pass        # an outstanding view keeps the mmap; the
                try:            # unlink below still drops the backing file
                    e.segment.unlink()
                except OSError:
                    pass

    def adopt(self, meta: ObjectMeta) -> bool:
        """Record an object whose segment was created by another process
        (a worker sealing a large task return). This is the main write path,
        so the store budget is enforced here. For arena-backed objects this
        is the Seal half of Create/Seal: the entry exists from
        ``alloc_in_arena`` and budget is already charged. Returns False
        when a sealed copy already exists (the caller still owns its
        segment and must clean it up)."""
        if meta.inline is not None and not isinstance(meta.inline, bytes):
            # inline metas in the oob band (>= transport_oob_threshold,
            # <= object_store_shm_threshold_bytes) decode as memoryviews into the
            # recv frame buffer; a store-resident copy must not pin that
            # whole frame (up to transport_max_batch_bytes) per object
            meta.inline = bytes(meta.inline)
        with self._lock:
            existing = self._entries.get(meta.object_id)
            if existing is not None:
                if not existing.sealed and meta.arena_ref is not None \
                        and existing.meta.arena_ref == meta.arena_ref:
                    existing.sealed = True
                    existing.writer_tag = None
                    existing.last_used = time.monotonic()
                    return True
                if not existing.sealed:
                    # a retried writer fell back to a different home
                    # (e.g. segment after its predecessor's orphaned
                    # Create): reclaim the stale allocation, adopt fresh
                    self._release_unsealed_locked(meta.object_id, existing)
                else:
                    return False
            # charge: segments/inline always; arena refs only when the
            # block lives in OUR arena (the ingest path of adopt_begin —
            # a foreign arena_ref is metadata about a remote node's copy)
            arena_owned = (meta.arena_ref is not None
                           and self._arena is not None
                           and meta.arena_ref[0] == self._arena.path)
            charged = bool(meta.shm_name or meta.inline) or arena_owned
            if charged:
                self._ensure_capacity(meta.size)
            if meta.shm_name:
                self._manifest_add(meta.shm_name)
            self._entries[meta.object_id] = _Entry(meta=meta, sealed=True,
                                                   charged=charged)
            self._used += meta.size if charged else 0
            return True

    # ------------------------------------------------------------------ get
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    # concurrency: requires(store.entries)
    def _touch(self, object_id: ObjectID) -> Optional[_Entry]:
        """Lookup + LRU touch + restore-if-spilled; callers hold _lock.
        Handing out a meta marks the entry read (see _Entry.ever_read)."""
        e = self._entries.get(object_id)
        if e is None or not e.sealed:
            return None
        e.last_used = time.monotonic()
        e.ever_read = True
        self._entries.move_to_end(object_id)
        if e.spilled_path is not None:
            self._restore(object_id, e)
        if e.lazy is not None:
            # the meta is about to leave this process: promote so it
            # names a mappable location
            self._materialize_locked(e)
        return e

    def get_meta(self, object_id: ObjectID) -> Optional[ObjectMeta]:
        with self._lock:
            e = self._touch(object_id)
            return e.meta if e is not None else None

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pinned += 1

    def pin_and_get(self, object_id: ObjectID) -> Optional[ObjectMeta]:
        """Atomically pin an object and return a live meta, restoring a
        spilled entry first. This is the dependency-resolution primitive:
        the pin keeps the segment mapped (spilling skips pinned entries)
        until the consuming task unpins — reference analogue: raylet
        ``PinObjectIDs`` before dispatch (``node_manager.proto:388``)."""
        with self._lock:
            e = self._touch(object_id)
            if e is None:
                return None
            e.pinned += 1
            return e.meta

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pinned > 0:
                e.pinned -= 1

    # concurrency: requires(store.entries)
    def _free_arena_block(self, e: _Entry) -> None:
        """Release an owned arena block; quarantine it if any reader may
        still hold zero-copy views into it (ADVICE r1: unconditional free
        reused blocks under live readers → silent corruption)."""
        off = e.meta.arena_ref[1]
        if e.ever_read and CONFIG.arena_free_quarantine_s > 0:
            self._quarantine.append(
                (time.monotonic() + CONFIG.arena_free_quarantine_s, off))
        else:
            self._arena.free(off)

    # concurrency: requires(store.entries)
    def _sweep_quarantine(self) -> None:
        """Callers hold _lock. Deadlines are appended in monotonic order
        (constant delay), so sweeping the prefix is enough. A block whose
        mapper refcount is still nonzero when its window expires (a
        reader process legitimately holding a long-lived zero-copy view,
        tracked by ``ArenaReader.tracked_buffer``) is requeued for
        another window instead of freed under the reader — the fixed
        window alone only covers readers that map *promptly*."""
        now = time.monotonic()
        requeue = []
        while self._quarantine and self._quarantine[0][0] <= now:
            _, off = self._quarantine.pop(0)
            rc = self._arena.refcount(off)
            if rc is not None and rc > 0:
                requeue.append(
                    (now + max(CONFIG.arena_free_quarantine_s, 1.0), off))
                continue
            self._arena.free(off)
        self._quarantine.extend(requeue)

    def free(self, object_ids: List[ObjectID]) -> None:
        with self._lock:
            if self._arena is not None:
                self._sweep_quarantine()
            for oid in object_ids:
                e = self._entries.pop(oid, None)
                if e is None:
                    continue
                if e.charged:
                    self._used -= e.meta.size
                if e.meta.arena_ref is not None:
                    # only the owning arena frees; adopted copies of
                    # another node's arena object are metadata-only
                    if (self._arena is not None
                            and e.meta.arena_ref[0] == self._arena.path):
                        self._free_arena_block(e)
                elif e.segment is not None:
                    try:
                        e.segment.close()
                        e.segment.unlink()
                    except FileNotFoundError:
                        pass
                elif e.meta.shm_name:
                    # segment created by a worker/driver process and adopted
                    # here by name only — unlink it via a fresh attachment
                    try:
                        seg = attach_segment(e.meta.shm_name)
                        seg.close()
                        seg.unlink()
                    except FileNotFoundError:
                        pass
                if e.spilled_path:
                    try:
                        os.unlink(e.spilled_path)
                    except OSError:
                        pass

    # -------------------------------------------------- network transfer
    def read_payload(self, object_id: ObjectID
                     ) -> Optional[Tuple[ObjectMeta, Optional[bytes]]]:
        """Raw wire bytes of an object, for cross-host pull (reference:
        ``object_manager.h:117`` Push/Pull). Inline/error values travel
        in the meta itself (payload None)."""
        return self.read_payload_chunk(object_id, 0, 1 << 62)

    def read_payload_chunk(self, object_id: ObjectID, offset: int,
                           length: int
                           ) -> Optional[Tuple[ObjectMeta, Optional[bytes]]]:
        """One bounded slice of an object's wire bytes (reference:
        chunked Push/Pull, ``object_manager.h:117`` — multi-GB objects
        must never become one socket frame). The entry is pinned during
        the copy so a concurrent spill can't unmap it; inline/error
        values ride the meta. A SPILLED object is served straight from
        its spill file — restoring the whole object per chunk would
        spill/restore-thrash for the length of the stream."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                return None
            e.last_used = time.monotonic()
            e.ever_read = True
            self._entries.move_to_end(object_id)
            if e.lazy is not None:
                self._materialize_locked(e)
            meta = e.meta
            if meta.inline is not None or meta.error is not None:
                return (meta, None)
            spilled = e.spilled_path
            if spilled is None:
                e.pinned += 1
        if spilled is not None:
            try:
                with open(spilled, "rb") as f:
                    f.seek(offset)
                    data = f.read(max(0, min(length, meta.size - offset)))
                return (meta, data)
            except OSError:
                # Either restored (file unlinked, entry now in memory) or
                # the spill file is genuinely gone. ONE bounded re-check
                # through the in-memory path — unbounded retries would
                # recurse forever on a deleted spill file.
                with self._lock:
                    e = self._entries.get(object_id)
                    if (e is None or not e.sealed
                            or e.spilled_path is not None):
                        return None       # still spilled & unreadable
                    e.pinned += 1
                # fall through to the in-memory read below
        try:
            end = min(offset + length, meta.size)
            if offset >= meta.size:
                return (meta, b"")
            meta = e.meta   # may have been rewritten by a restore
            if (meta.arena_ref is not None and self._arena is not None
                    and meta.arena_ref[0] == self._arena.path):
                buf = self._arena.buffer(meta.arena_ref[1], meta.size)
                data = bytes(buf[offset:end])
            elif meta.shm_name is not None:
                seg = e.segment
                if seg is None:
                    # cache the attachment: a streamed pull reads many
                    # chunks, and re-mmapping the segment per chunk is
                    # pure overhead (freed with the entry)
                    seg = attach_segment(meta.shm_name)
                    with self._lock:
                        if e.segment is None:
                            e.segment = seg
                        elif seg is not e.segment:
                            seg.close()
                            seg = e.segment
                data = bytes(seg.buf[offset:end])
            else:
                return None
            return (meta, data)
        finally:
            self.unpin(object_id)

    def adopt_begin(self, object_id: ObjectID, size: int) -> "_AdoptWriter":
        """Incremental adoption of a pulled copy: allocate the backing
        store up front, stream chunks in, then finish() seals it as a
        local secondary copy.

        Prefers a RAW arena block so the PR-4 OOB frames land with one
        mmap write (recv buffer → arena; no private-segment intermediate
        and no extra first-touch faulting). The block is deliberately NOT
        registered as an entry until finish(): an unsealed entry would
        let a concurrent adopt() of the same id (e.g. a local
        reconstruction finishing mid-pull) treat it as an abandoned
        writer and free the block the streaming writer is still copying
        into — finish() adopts (charging the budget then) or frees the
        block on a lost race. Falls back to a private segment when the
        arena is absent/full/out of size class."""
        off = None
        if self._arena is not None and size <= self.ARENA_MAX_OBJECT:
            with self._lock:
                self._sweep_quarantine()
                self._ensure_capacity(size)
                off = self._arena.alloc(size)
        if off is not None:
            return _AdoptWriter(self, object_id, size, arena_off=off)
        seg = shared_memory.SharedMemory(
            create=True, size=max(size, 1),
            name=_adopt_segment_name(object_id))
        self._manifest_add(seg.name)
        return _AdoptWriter(self, object_id, size, segment=seg)

    def adopt_payload(self, object_id: ObjectID, data: bytes) -> ObjectMeta:
        """Store a pulled copy of a remote object as a local secondary
        copy (never published to the directory — the primary stays with
        the owner). Only used cross-host, so the deterministic segment
        name cannot collide with the owner's."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.sealed:
                return e.meta
        size = len(data)
        ref = self.alloc_in_arena(object_id, size)
        if ref is not None:
            self._arena.buffer(ref[1], size)[:] = data
            meta = ObjectMeta(object_id=object_id, size=size, arena_ref=ref)
        else:
            # distinct name: never collides with the owner's segment when
            # "cross-host" is simulated on one machine (RTPU_NODE_HOST)
            seg = shared_memory.SharedMemory(
                create=True, size=max(size, 1),
                name=_adopt_segment_name(object_id))
            seg.buf[:size] = data
            name = seg.name
            seg.close()
            meta = ObjectMeta(object_id=object_id, size=size, shm_name=name)
        if not self.adopt(meta):
            # A concurrent pull sealed a copy first: ours is redundant
            # and must not leak (unique names mean this race no longer
            # errors out). Arena case: our unsealed Create was already
            # reclaimed by the winner's adopt (_release_unsealed_locked),
            # so freeing again here would double-free — only the private
            # shm segment is still ours to unlink.
            if meta.arena_ref is None:
                try:
                    s = shared_memory.SharedMemory(name=meta.shm_name)
                    s.close()
                    s.unlink()
                except OSError:
                    pass
            with self._lock:
                e = self._entries.get(object_id)
                if e is not None and e.sealed:
                    return e.meta
            # winner evicted between adopt() and the re-lookup: our copy
            # is gone too (unlinked/reclaimed above) — redo the adoption
            # from the payload we still hold
            return self.adopt_payload(object_id, data)
        return meta

    def create_local(self, object_id: ObjectID, size: int
                     ) -> Tuple[memoryview, ObjectMeta]:
        """Writable destination for a SAME-PROCESS writer (the head
        driver): an arena block when possible, else an owned segment.
        The caller fills the view, then calls ``seal(object_id)`` —
        no ALLOC/PUT round trips (reference analogue: the CoreWorker's
        local plasma client)."""
        ref = self.alloc_in_arena(object_id, size)
        if ref is not None:
            with self._lock:
                meta = self._entries[object_id].meta
            return self._arena.buffer(ref[1], size)[:size], meta
        buf = self.create(object_id, size)
        with self._lock:
            meta = self._entries[object_id].meta
        return buf, meta

    def put_payload(self, object_id: ObjectID, data) -> ObjectMeta:
        """Materialize wire bytes as the local PRIMARY copy, landing
        them directly in an arena block when possible. ``data`` may be
        a zero-copy memoryview into a transport frame buffer (pickle-5
        out-of-band), so this is the payload's only copy after it left
        the socket. Used for cross-host driver puts (PUT_OBJECT_WIRE)."""
        size = len(data)
        ref = self.alloc_in_arena(object_id, size)
        if ref is not None:
            self._arena.buffer(ref[1], size)[:] = data
            meta = ObjectMeta(object_id=object_id, size=size,
                              arena_ref=ref)
            self.adopt(meta)            # the Seal half of Create/Seal
            return meta
        seg = create_segment(object_id, size)
        try:
            seg.buf[:size] = data
            name = seg.name
        finally:
            seg.close()
        meta = ObjectMeta(object_id=object_id, size=size, shm_name=name)
        if not self.adopt(meta):
            # a sealed copy already exists (duplicate put): ours is
            # redundant and must not leak the segment
            try:
                s = shared_memory.SharedMemory(name=name)
                s.close()
                s.unlink()
            except OSError:
                pass
            existing = self.get_meta(object_id)
            if existing is not None:
                return existing
        return meta

    def objects_snapshot(self) -> Dict[ObjectID, tuple]:
        """Per-object introspection view: ``oid -> (pinned_count,
        spilled)`` for every sealed entry. Feeds the PINNED_IN_STORE /
        spilled columns of ``state.list_objects()`` (pin counts are
        node-local store facts the control-plane ledger can't know)."""
        with self._lock:
            return {oid: (e.pinned, e.spilled_path is not None)
                    for oid, e in self._entries.items() if e.sealed}

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = {
                "num_objects": len(self._entries),
                "used_bytes": self._used,
                "capacity_bytes": self._capacity,
                "num_spilled": self.num_spilled,
                "num_restored": self.num_restored,
                "spilled_bytes_total": self.spilled_bytes_total,
                "restored_bytes_total": self.restored_bytes_total,
                "num_lazy_puts": self.num_lazy_puts,
                "num_materialized": self.num_materialized,
                "arena_enabled": int(self._arena is not None),
            }
            shm_bytes = 0
            for e in self._entries.values():
                m = e.meta
                if m.shm_name is not None or (
                        m.arena_ref is not None and self._arena is not None
                        and m.arena_ref[0] == self._arena.path):
                    shm_bytes += m.size
            out["shm_bytes"] = shm_bytes
            if self._arena is not None:
                out["arena_used_bytes"] = self._arena.used
                out["arena_capacity_bytes"] = self._arena.capacity
                out["arena_num_blocks"] = self._arena.num_blocks
                out["arena_quarantined_blocks"] = len(self._quarantine)
            return out

    def drain_spill_events(self) -> List[tuple]:
        """Hand the accumulated ("spill"|"restore", oid, size) records to
        the node service, which emits the attributed cluster events and
        byte counters outside the store lock."""
        with self._lock:
            if not self._spill_events:
                return []
            out, self._spill_events = self._spill_events, []
            return out

    # ------------------------------------------------------- spill/restore
    # concurrency: requires(store.entries)
    def _ensure_capacity(self, incoming: int) -> None:
        threshold = CONFIG.object_store_spill_threshold * self._capacity
        if self._used + incoming <= threshold:
            return
        for oid in list(self._entries):
            if self._used + incoming <= threshold:
                break
            e = self._entries[oid]
            if not (e.sealed and e.pinned == 0 and e.spilled_path is None
                    and e.charged):
                continue
            if e.lazy is not None or e.meta.shm_name is not None:
                self._spill(oid, e)
            elif e.meta.arena_ref is not None:
                # a READ arena entry may have live zero-copy views into
                # its block; spill it only when the cross-process mapper
                # refcount proves it idle (the block still rides the free
                # quarantine so a reader holding just the meta reads the
                # intact bytes until the window drains). No refcount API
                # (older .so) → stay conservative: unread entries only.
                rc = (self._arena.refcount(e.meta.arena_ref[1])
                      if (self._arena is not None
                          and e.meta.arena_ref[0] == self._arena.path)
                      else None)
                if not e.ever_read or rc == 0:
                    self._spill(oid, e)

    # concurrency: requires(store.entries)
    def _spill(self, object_id: ObjectID, e: _Entry) -> None:
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir, _segment_name(object_id))
        if e.lazy is not None:
            # lazy primary under pressure: serialize straight to disk —
            # the value never transits shm at all (put → disk, one copy)
            smeta, views = e.lazy
            with open(path, "wb") as f:
                ser.write_file(f, smeta, views)
            e.lazy = None
            e.meta.flags &= ~ObjectMeta.LAZY
        elif e.meta.arena_ref is not None:
            if (self._arena is None
                    or e.meta.arena_ref[0] != self._arena.path):
                return
            off = e.meta.arena_ref[1]
            with open(path, "wb") as f:
                f.write(self._arena.buffer(off, e.meta.size))
            # quarantined, not freed, when the entry was ever read: a
            # reader still holding the meta keeps reading the intact old
            # bytes until the window (and its mapper refcount) drains,
            # after which its incref fails cleanly and it re-GETs
            self._free_arena_block(e)
            e.meta.arena_ref = None
        else:
            seg = e.segment
            if seg is None:
                # adopted segment: created by a worker/driver, attach by name
                try:
                    seg = attach_segment(e.meta.shm_name)
                except FileNotFoundError:
                    return
            with open(path, "wb") as f:
                f.write(seg.buf[:e.meta.size])
            seg.close()
            try:
                seg.unlink()
            except FileNotFoundError:
                pass
            e.segment = None
            e.meta.shm_name = None
        e.spilled_path = path
        e.meta.flags |= ObjectMeta.SPILLED
        self._used -= e.meta.size
        e.charged = False
        self.num_spilled += 1
        self.spilled_bytes_total += e.meta.size
        self._spill_events.append(("spill", e.meta.object_id, e.meta.size))

    # concurrency: requires(store.entries)
    def _restore(self, object_id: ObjectID, e: _Entry) -> None:
        self._ensure_capacity(e.meta.size)
        off = (self._arena.alloc(e.meta.size)
               if (self._arena is not None
                   and e.meta.size <= self.ARENA_MAX_OBJECT) else None)
        if off is not None:
            with open(e.spilled_path, "rb") as f:
                f.readinto(self._arena.buffer(off, e.meta.size))
            e.meta.arena_ref = (self._arena.path, off)
        else:
            seg = shared_memory.SharedMemory(
                create=True, size=max(e.meta.size, 1),
                name=_segment_name(object_id))
            self._manifest_add(seg.name)
            with open(e.spilled_path, "rb") as f:
                f.readinto(seg.buf[:e.meta.size])
            e.segment = seg
            e.meta.shm_name = seg.name
        os.unlink(e.spilled_path)
        e.spilled_path = None
        e.meta.flags &= ~ObjectMeta.SPILLED
        self._used += e.meta.size
        e.charged = True
        self.num_restored += 1
        self.restored_bytes_total += e.meta.size
        self._spill_events.append(("restore", e.meta.object_id, e.meta.size))

    def shutdown(self) -> None:
        with self._lock:
            self.free(list(self._entries))
            if self._arena is not None:
                self._arena.close(unlink=True)
                self._arena = None
            if self._manifest_f is not None:
                try:
                    self._manifest_f.close()
                    os.unlink(self._manifest_path)
                except OSError:
                    pass
                self._manifest_f = None


class _AdoptWriter:
    """Streaming target for a chunked cross-host pull — an unregistered
    arena block (preferred; OOB frames land with one mmap write) or a
    private segment. Not registered in the store until finish() — a
    half-written copy must never be readable (or freeable) under its
    object id."""

    def __init__(self, store: "ObjectStore", object_id: ObjectID, size: int,
                 segment: Optional[shared_memory.SharedMemory] = None,
                 arena_off: Optional[int] = None):
        self._store = store
        self._oid = object_id
        self._size = size
        self._segment = segment
        self._arena_off = arena_off
        self._buf = (store._arena.buffer(arena_off, size)
                     if arena_off is not None else None)

    def write(self, offset: int, data) -> None:
        if self._buf is not None:
            self._buf[offset:offset + len(data)] = data
        else:
            self._segment.buf[offset:offset + len(data)] = data

    def finish(self) -> ObjectMeta:
        if self._arena_off is not None:
            meta = ObjectMeta(object_id=self._oid, size=self._size,
                              arena_ref=(self._store._arena.path,
                                         self._arena_off))
        else:
            meta = ObjectMeta(object_id=self._oid, size=self._size,
                              shm_name=self._segment.name)
        if not self._store.adopt(meta):
            # a sealed copy landed mid-stream (e.g. local reconstruction
            # finished first): ours is redundant — free it or it leaks
            existing = self._store.get_meta(self._oid)
            self.abort()
            return existing if existing is not None else meta
        if self._segment is not None:
            self._segment.close()
        return meta

    def abort(self) -> None:
        if self._arena_off is not None:
            # never registered, never read: immediate free is safe
            self._buf = None
            self._store._arena.free(self._arena_off)
            self._arena_off = None
            return
        try:
            self._segment.close()
            self._segment.unlink()
        except OSError:
            pass


# --------------------------------------------------------------- client side

def read_wire_bytes(meta: ObjectMeta) -> Optional[bytes]:
    """Copy an object's serialized payload out of its backing storage
    (any same-host segment/arena, not necessarily this process's store).
    Used to inline payloads into replies for cross-host drivers."""
    if meta.inline is not None:
        return meta.inline
    if meta.arena_ref is not None:
        from . import native
        path, off = meta.arena_ref
        # tracked: the incref pins the block against spill/reuse for the
        # duration of the copy; raises FileNotFoundError on a stale meta
        # (block already freed) exactly like a vanished segment would
        return bytes(native.ArenaReader.get(path).tracked_buffer(
            off, meta.size))
    if meta.shm_name is not None:
        seg = attach_segment(meta.shm_name)
        try:
            return bytes(seg.buf[:meta.size])
        finally:
            seg.close()
    return None


@fieldsan.guarded
class ObjectReader:
    """Per-process cache of attached segments for zero-copy reads."""

    def __init__(self):
        self._segments: Dict[str, shared_memory.SharedMemory] = {}
        self._lock = locksan.lock("store.reader_segments")

    def load(self, meta: ObjectMeta):
        from . import serialization

        if meta.is_error():
            raise serialization.from_bytes(meta.error)
        if meta.inline is not None:
            return serialization.from_bytes(meta.inline)
        if meta.arena_ref is not None:
            from . import native
            path, off = meta.arena_ref
            reader = native.ArenaReader.get(path)
            # tracked_buffer increfs the block's cross-process mapper
            # refcount and decrefs when the last zero-copy view dies, so
            # the owner defers free/spill while this process reads.
            # FileNotFoundError (stale meta, block freed) propagates to
            # the client's bounded re-GET, same as a vanished segment.
            return serialization.read_from(
                reader.tracked_buffer(off, meta.size))
        with self._lock:
            seg = self._segments.get(meta.shm_name)
            if seg is None:
                seg = attach_segment(meta.shm_name)
                self._segments[meta.shm_name] = seg
        return serialization.read_from(seg.buf[:meta.size])

    def release(self, shm_name: str) -> None:
        with self._lock:
            seg = self._segments.pop(shm_name, None)
        if seg is not None:
            seg.close()

    def close(self) -> None:
        with self._lock:
            for seg in self._segments.values():
                try:
                    seg.close()
                except Exception:
                    pass
            self._segments.clear()
