"""Shared-memory object store (plasma-equivalent).

Equivalent role to the reference's plasma store
(``src/ray/object_manager/plasma/store.h:55`` — shm segments + allocator +
LRU eviction + spilling). Design differences, on purpose:

- Objects are immutable, one POSIX shm segment per large object
  (``multiprocessing.shared_memory``) instead of one dlmalloc arena — the
  kernel is our allocator; small objects are carried inline in RPC frames
  (reference analogue: in-memory store, ``memory_store.h:43``).
- Readers in any process attach by name for zero-copy access (pickle-5
  out-of-band buffers point straight into the mapping), standing in for
  plasma's fd-passing (``fling.cc``).
- When the store exceeds its budget, least-recently-used unpinned primary
  copies are spilled to disk files and restored on demand (reference
  analogue: ``local_object_manager.h:110`` + ``external_storage.py:246``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import shared_memory, resource_tracker
from typing import Dict, List, Optional

from .config import CONFIG
from .ids import ObjectID

_SHM_PREFIX = "rtpu"


def _segment_name(object_id: ObjectID) -> str:
    # Full 32-hex-char id: put ids carry only 8 random bytes (the rest is
    # owner entropy), so truncating here would leave too little entropy
    # and collide segment names at scale.
    return f"{_SHM_PREFIX}{object_id.hex()}"


def create_segment(object_id: ObjectID, size: int) -> shared_memory.SharedMemory:
    """Create a named segment from a non-authority process (worker/driver
    writing a large object directly). Unregistered from the resource tracker
    because lifetime is owned by the node store that adopts it."""
    seg = shared_memory.SharedMemory(
        create=True, size=max(size, 1), name=_segment_name(object_id))
    try:
        resource_tracker.unregister(seg._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    return seg


class _AttachedSegment(shared_memory.SharedMemory):
    """Reader-side attachment. Swallows the BufferError raised at interpreter
    exit when user code still holds zero-copy numpy views into the mapping
    (the OS reclaims it anyway)."""

    def __del__(self):
        try:
            super().__del__()
        except BufferError:
            pass


def attach_segment(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment. Python 3.12's SharedMemory registers
    with the resource tracker only on create, so attaching needs no
    unregister dance; cleanup is owned by the node store."""
    return _AttachedSegment(name=name)


@dataclass
class ObjectMeta:
    """Where an object's value lives; travels in RPC messages."""

    object_id: ObjectID
    size: int
    inline: Optional[bytes] = None  # wire-format bytes, for small objects
    shm_name: Optional[str] = None  # segment name, for large objects
    error: Optional[bytes] = None   # pickled exception, for failed tasks
    node_hint: Optional[bytes] = None  # NodeID binary of a known location

    def is_error(self) -> bool:
        return self.error is not None


@dataclass
class _Entry:
    meta: ObjectMeta
    segment: Optional[shared_memory.SharedMemory] = None
    sealed: bool = False
    pinned: int = 0
    spilled_path: Optional[str] = None
    last_used: float = field(default_factory=time.monotonic)
    charged: bool = False  # whether meta.size is counted in store._used


class ObjectStore:
    """Node-local authority over object values.

    Thread-safe; used from the node service event loop and (for driver-side
    fast-path puts) the driver thread.
    """

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self._lock = threading.RLock()
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()
        self._capacity = capacity_bytes or CONFIG.object_store_memory_mb * (1 << 20)
        self._used = 0
        self._spill_dir = spill_dir or CONFIG.spill_directory or "/tmp/rtpu_spill"
        self.num_spilled = 0
        self.num_restored = 0

    # ------------------------------------------------------------------ put
    def put_inline(self, object_id: ObjectID, data: bytes) -> ObjectMeta:
        meta = ObjectMeta(object_id=object_id, size=len(data), inline=data)
        with self._lock:
            self._ensure_capacity(len(data))
            self._entries[object_id] = _Entry(meta=meta, sealed=True,
                                              charged=True)
            self._used += len(data)
        return meta

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        """Allocate a shm segment; caller fills it then calls seal()."""
        with self._lock:
            self._ensure_capacity(size)
            seg = shared_memory.SharedMemory(
                create=True, size=max(size, 1), name=_segment_name(object_id))
            meta = ObjectMeta(object_id=object_id, size=size,
                              shm_name=seg.name)
            self._entries[object_id] = _Entry(meta=meta, segment=seg,
                                              charged=True)
            self._used += size
            return seg.buf[:size]

    def seal(self, object_id: ObjectID) -> ObjectMeta:
        with self._lock:
            entry = self._entries[object_id]
            entry.sealed = True
            entry.last_used = time.monotonic()
            return entry.meta

    def put_error(self, object_id: ObjectID, error: bytes) -> ObjectMeta:
        meta = ObjectMeta(object_id=object_id, size=len(error), error=error)
        with self._lock:
            self._entries[object_id] = _Entry(meta=meta, sealed=True)
        return meta

    def adopt(self, meta: ObjectMeta) -> None:
        """Record an object whose segment was created by another process
        (a worker sealing a large task return). This is the main write path,
        so the store budget is enforced here."""
        with self._lock:
            if meta.object_id in self._entries:
                return
            charged = bool(meta.shm_name or meta.inline)
            if charged:
                self._ensure_capacity(meta.size)
            self._entries[meta.object_id] = _Entry(meta=meta, sealed=True,
                                                   charged=charged)
            self._used += meta.size if charged else 0

    # ------------------------------------------------------------------ get
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def _touch(self, object_id: ObjectID) -> Optional[_Entry]:
        """Lookup + LRU touch + restore-if-spilled; callers hold _lock."""
        e = self._entries.get(object_id)
        if e is None or not e.sealed:
            return None
        e.last_used = time.monotonic()
        self._entries.move_to_end(object_id)
        if e.spilled_path is not None:
            self._restore(object_id, e)
        return e

    def get_meta(self, object_id: ObjectID) -> Optional[ObjectMeta]:
        with self._lock:
            e = self._touch(object_id)
            return e.meta if e is not None else None

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pinned += 1

    def pin_and_get(self, object_id: ObjectID) -> Optional[ObjectMeta]:
        """Atomically pin an object and return a live meta, restoring a
        spilled entry first. This is the dependency-resolution primitive:
        the pin keeps the segment mapped (spilling skips pinned entries)
        until the consuming task unpins — reference analogue: raylet
        ``PinObjectIDs`` before dispatch (``node_manager.proto:388``)."""
        with self._lock:
            e = self._touch(object_id)
            if e is None:
                return None
            e.pinned += 1
            return e.meta

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pinned > 0:
                e.pinned -= 1

    def free(self, object_ids: List[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                e = self._entries.pop(oid, None)
                if e is None:
                    continue
                if e.charged:
                    self._used -= e.meta.size
                if e.segment is not None:
                    try:
                        e.segment.close()
                        e.segment.unlink()
                    except FileNotFoundError:
                        pass
                elif e.meta.shm_name:
                    # segment created by a worker/driver process and adopted
                    # here by name only — unlink it via a fresh attachment
                    try:
                        seg = attach_segment(e.meta.shm_name)
                        seg.close()
                        seg.unlink()
                    except FileNotFoundError:
                        pass
                if e.spilled_path:
                    try:
                        os.unlink(e.spilled_path)
                    except OSError:
                        pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "used_bytes": self._used,
                "capacity_bytes": self._capacity,
                "num_spilled": self.num_spilled,
                "num_restored": self.num_restored,
            }

    # ------------------------------------------------------- spill/restore
    def _ensure_capacity(self, incoming: int) -> None:
        threshold = CONFIG.object_spilling_threshold * self._capacity
        if self._used + incoming <= threshold:
            return
        for oid in list(self._entries):
            if self._used + incoming <= threshold:
                break
            e = self._entries[oid]
            if (e.sealed and e.pinned == 0 and e.meta.shm_name is not None
                    and e.spilled_path is None):
                self._spill(oid, e)

    def _spill(self, object_id: ObjectID, e: _Entry) -> None:
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir, _segment_name(object_id))
        seg = e.segment
        if seg is None:
            # adopted segment: created by a worker/driver, attach by name
            try:
                seg = attach_segment(e.meta.shm_name)
            except FileNotFoundError:
                return
        with open(path, "wb") as f:
            f.write(seg.buf[:e.meta.size])
        e.spilled_path = path
        seg.close()
        try:
            seg.unlink()
        except FileNotFoundError:
            pass
        e.segment = None
        e.meta.shm_name = None
        self._used -= e.meta.size
        e.charged = False
        self.num_spilled += 1

    def _restore(self, object_id: ObjectID, e: _Entry) -> None:
        self._ensure_capacity(e.meta.size)
        seg = shared_memory.SharedMemory(
            create=True, size=max(e.meta.size, 1), name=_segment_name(object_id))
        with open(e.spilled_path, "rb") as f:
            f.readinto(seg.buf[:e.meta.size])
        os.unlink(e.spilled_path)
        e.spilled_path = None
        e.segment = seg
        e.meta.shm_name = seg.name
        self._used += e.meta.size
        e.charged = True
        self.num_restored += 1

    def shutdown(self) -> None:
        with self._lock:
            self.free(list(self._entries))


# --------------------------------------------------------------- client side

class ObjectReader:
    """Per-process cache of attached segments for zero-copy reads."""

    def __init__(self):
        self._segments: Dict[str, shared_memory.SharedMemory] = {}
        self._lock = threading.Lock()

    def load(self, meta: ObjectMeta):
        from . import serialization

        if meta.is_error():
            raise serialization.from_bytes(meta.error)
        if meta.inline is not None:
            return serialization.from_bytes(meta.inline)
        with self._lock:
            seg = self._segments.get(meta.shm_name)
            if seg is None:
                seg = attach_segment(meta.shm_name)
                self._segments[meta.shm_name] = seg
        return serialization.read_from(seg.buf[:meta.size])

    def release(self, shm_name: str) -> None:
        with self._lock:
            seg = self._segments.pop(shm_name, None)
        if seg is not None:
            seg.close()

    def close(self) -> None:
        with self._lock:
            for seg in self._segments.values():
                try:
                    seg.close()
                except Exception:
                    pass
            self._segments.clear()
