"""Wire protocol between drivers/workers and the node service.

Equivalent role to the reference's gRPC surface (``protobuf/core_worker.proto``,
``node_manager.proto``): task push, object status, actor control. We use
length-prefixed pickled frames over unix-domain sockets — the control plane
is local to a host; cross-host transfer rides the object plane (shm on one
host, chunked TCP between hosts in the multi-node deployment).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID

_LEN = struct.Struct("<I")

# ----------------------------------------------------------------- opcodes
# client -> service
REGISTER = 1            # (kind, worker_id, pid)
SUBMIT_TASK = 2         # TaskSpec
CREATE_ACTOR = 3        # ActorSpec
SUBMIT_ACTOR_TASK = 4   # TaskSpec (actor_id set)
PUT_OBJECT = 5          # ObjectMeta
GET_OBJECTS = 6         # (req_id, [ObjectID], timeout_s|None)
WAIT_OBJECTS = 7        # (req_id, [ObjectID], num_returns, timeout_s)
FREE_OBJECTS = 8        # [ObjectID]
KILL_ACTOR = 9          # (ActorID, no_restart)
CANCEL_TASK = 10        # (TaskID, force)
GET_NAMED_ACTOR = 11    # (req_id, name, namespace)
KV_PUT = 12             # (key, value, overwrite)
KV_GET = 13             # (req_id, key)
KV_DEL = 14             # key
KV_KEYS = 15            # (req_id, prefix)
FETCH_FUNCTION = 16     # (req_id, function_id)
CLUSTER_INFO = 17       # (req_id, what)
TASK_DONE = 18          # (task_id, [ObjectMeta], error|None, is_actor_creation)
CREATE_PG = 19          # PlacementGroupSpec
REMOVE_PG = 20          # PlacementGroupID
ACTOR_EXIT = 21         # (actor_id, reason)
SUBSCRIBE_EVENTS = 22   # (req_id, channel)
STATE_QUERY = 23        # (req_id, what, filters)
PROFILE_EVENT = 24      # (kind, payload)
PUT_OBJECT_SYNC = 25    # (req_id, ObjectMeta) — acked once the store adopts it
ALLOC_OBJECT = 26       # (req_id, ObjectID, size) — arena Create; reply
                        # INFO_REPLY (arena_path, offset) | None

# node <-> node (network plane; reference analogues:
# ``node_manager.proto:363`` RequestWorkerLease/forwarding and
# ``object_manager.h:117`` Push/Pull)
NODE_POST = 27          # item tuple, enqueued on the peer's event loop
OBJ_GET_META = 28       # (req_id, ObjectID, pin) -> INFO_REPLY meta|None
OBJ_UNPIN = 29          # ObjectID
# op 30 retired: whole-payload OBJ_PULL, superseded by OBJ_PULL_CHUNK
PG_RESERVE = 31         # (req_id, pg_key, demand) -> INFO_REPLY bool
PG_RELEASE = 32         # pg_key
NODE_STATS = 33         # (req_id, what) -> INFO_REPLY payload

# client/node <-> GCS service (reference: ``gcs_service.proto:63-699``)
GCS_CALL = 34           # (req_id, method, args, kwargs) -> INFO_REPLY
GCS_CAST = 35           # (method, args, kwargs) — no reply (hot mutators)
GCS_SUBSCRIBE = 36      # channel — pushes EVENT (channel, payload) frames

# distributed reference counting (reference: ``reference_count.h:61``)
REF_REGISTER = 37       # ObjectID — this client now holds a reference
REF_DROP = 38           # ObjectID — this client's last local ref died
REF_BATCH = 39          # [(op, ObjectID), ...] — coalesced edge stream

# Cross-host driver data plane (Ray-Client-equivalent attach: the driver
# shares no /dev/shm with the cluster, so payloads ride the socket).
# Numbered after the reply range — 40-51 are already taken below.
GET_OBJECTS_FETCH = 52  # (req_id, [ObjectID], timeout) — GET_REPLY metas
                        # with shm/arena payloads converted to inline
PUT_OBJECT_WIRE = 53    # (req_id, ObjectID, bytes) — node materializes
                        # the payload in ITS store and seals

# Worker blocked in a get(): release its CPU so nested tasks can run
# (reference: NotifyDirectCallTaskBlocked/Unblocked, core_worker.cc)
NOTIFY_BLOCKED = 54     # no payload
NOTIFY_UNBLOCKED = 55   # no payload

# Chunked cross-host pull (reference: object_manager.h:117 Push/Pull in
# bounded chunks — a multi-GB object must never be one socket frame)
OBJ_PULL_CHUNK = 56     # (req_id, ObjectID, offset, length)
                        # -> INFO_REPLY (meta, bytes|None)|None

# Coalesced submission stream: [(SUBMIT_TASK|SUBMIT_ACTOR_TASK, spec),
# ...] — one frame + one dispatcher wakeup per burst (reference
# analogue: the C++ submit queue amortizing per-call overhead)
SUBMIT_BATCH = 57

# Streaming generator returns (reference: ReportGeneratorItemReturns,
# ``core_worker.proto:396``; consumer surface ``_raylet.pyx:252``
# ObjectRefGenerator)
GEN_ITEM = 58           # worker -> node: (task_id, index, ObjectMeta)
GEN_ACK = 59            # node -> worker push: (task_id, consumed_count)
GEN_NEXT = 60           # (req_id, task_id, index) -> INFO_REPLY
                        #   ("item", meta) | ("end", count)
                        #   | ("error", err_bytes)
GEN_CLOSE = 61          # (task_id,) — consumer dropped the generator
EXECUTE_BATCH = 62      # node -> worker: [EXECUTE_TASK payload, ...]
# op 63 reserved (was TASK_DONE_BATCH; DONEs leave per task so an
# early result is never withheld behind a slow batch successor)
CANCEL_QUEUED = 64      # node -> worker: task_id queued behind current
RETURN_LEASED = 65      # worker -> node: [task_id] unstarted leased tasks
RETURN_REFS = 66        # worker -> node: (return_oid, [contained oids]) —
                        # refs pickled INSIDE a return; pinned until the
                        # return object is freed (sent before TASK_DONE)

# Distributed debugging (reference analogues: ``ray stack`` shelling
# py-spy over worker pids, and the profiling hooks). Collection fans
# out over the node plane; per-process replies ride the same conn the
# request arrived on, answered by the RECEIVER's reader thread — which
# is never the thread blocked in user code, so a worker wedged in get()
# still reports its stack.
CLUSTER_STACKS = 67     # driver -> node: (req_id, timeout_s)
                        # -> INFO_REPLY {"nodes": {...}, "groups": [...]}
CLUSTER_PROFILE = 68    # driver -> node: (req_id, opts dict)
                        # -> INFO_REPLY {"nodes": {...}, "collapsed": {...}}
STACK_DUMP = 69         # node -> worker/driver push: token
STACK_REPLY = 70        # worker/driver -> node: (token, dump dict)
PROFILE_START = 71      # node -> worker push: (token, opts dict)
PROFILE_REPORT = 72     # worker -> node: (token, report dict)

# service -> client
EXECUTE_TASK = 40       # (TaskSpec, {ObjectID: ObjectMeta} resolved deps)
GET_REPLY = 41          # (req_id, [ObjectMeta])
WAIT_REPLY = 42         # (req_id, [ready ObjectID], [pending ObjectID])
NAMED_ACTOR_REPLY = 43  # (req_id, actor_info | None)
KV_REPLY = 44           # (req_id, value)
FUNCTION_REPLY = 45     # (req_id, blob | None)
INFO_REPLY = 46         # (req_id, payload)
ACTOR_STATE = 47        # (actor_id, state, reason) pushed to interested clients
SHUTDOWN = 48           # ()
EVENT = 49              # (channel, payload)
ERROR_REPLY = 50        # (req_id, pickled exception)
PUT_REPLY = 51          # (req_id,)

KIND_DRIVER = 0
KIND_WORKER = 1


# ------------------------------------------------------------------- specs

def _mk_task_spec(t: tuple) -> "TaskSpec":
    """Rebuild a TaskSpec from its flattened wire tuple (see
    ``TaskSpec.__reduce__``). Positional layout == dataclass field
    order; __new__ + direct assignment skips __init__ overhead."""
    s = TaskSpec.__new__(TaskSpec)
    (tid, jid, s.name, s.function_id, s.args, s.kwargs, s.num_returns,
     rids, s.resources, s.max_retries, s.retry_exceptions, aid,
     s.method_name, s.seq_no, s.scheduling_strategy, s.owner_id,
     s.origin_node_id, s.namespace, s.runtime_env, s.trace_context,
     s.accel_ids) = t
    s.task_id = TaskID(tid)
    s.job_id = JobID(jid)
    s.return_ids = [ObjectID(b) for b in rids]
    s.actor_id = ActorID(aid) if aid is not None else None
    return s


@dataclass
class TaskSpec:
    """Immutable description of a task invocation.

    Reference analogue: ``TaskSpecification``
    (``src/ray/common/task/task_spec.h:244``).
    """

    task_id: TaskID
    job_id: JobID
    name: str
    function_id: bytes                       # content hash of the pickled fn
    # each arg: ("v", wire_bytes) inline value | ("r", ObjectID) reference
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs: Dict[str, Tuple[str, Any]] = field(default_factory=dict)
    num_returns: int = 1
    return_ids: List[ObjectID] = field(default_factory=list)
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    # actor-related
    actor_id: Optional[ActorID] = None       # set for actor method calls
    method_name: str = ""
    seq_no: int = 0                          # actor call ordering
    # scheduling
    scheduling_strategy: Any = None          # None | "SPREAD" | NodeAffinity | PG
    owner_id: bytes = b""                    # WorkerID binary of the submitter
    # NodeID binary of the node that owns/routes this task; a starved
    # target spills the task back here for re-routing (reference
    # analogue: lease spillback keeps the owner in the loop)
    origin_node_id: bytes = b""
    namespace: str = "default"               # submitter's job namespace
    runtime_env: Optional[dict] = None       # validated runtime env
    # tracing: caller's (trace_id, span_id), propagated into the worker
    # (reference: ray.util.tracing traceparent in the task spec)
    trace_context: Optional[dict] = None
    # per-instance accelerator slots assigned by the executing node at
    # dispatch (reference: resource-instance ids / GPU id assignment);
    # read via get_runtime_context().get_accelerator_ids()
    accel_ids: Optional[List[int]] = None

    def __reduce__(self):
        # Hot-path serialization: a task spec crosses the wire 2-3 times
        # per invocation (submit, dispatch, peer forward). The default
        # dataclass pickle costs ~28us/spec (per-object reduce of every
        # ID); flattening to one tuple with IDs as raw bytes is ~8us.
        # tests/test_core_basic.py::test_spec_wire_roundtrip guards the
        # field list against drift.
        return (_mk_task_spec, (
            (self.task_id.binary(), self.job_id.binary(), self.name,
             self.function_id, self.args, self.kwargs, self.num_returns,
             [r.binary() for r in self.return_ids], self.resources,
             self.max_retries, self.retry_exceptions,
             self.actor_id.binary() if self.actor_id else None,
             self.method_name, self.seq_no, self.scheduling_strategy,
             self.owner_id, self.origin_node_id, self.namespace,
             self.runtime_env, self.trace_context, self.accel_ids),))


@dataclass
class ActorSpec:
    """Actor creation description (reference: actor creation TaskSpec +
    ``gcs_actor_manager.h:281`` registration payload)."""

    actor_id: ActorID
    job_id: JobID
    name: str                                # class name for display
    registered_name: Optional[str] = None    # named-actor name
    namespace: str = "default"
    class_blob: bytes = b""                  # cloudpickled class
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs: Dict[str, Tuple[str, Any]] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_concurrency: int = 1
    is_async: bool = False
    lifetime: Optional[str] = None           # None | "detached"
    scheduling_strategy: Any = None
    creation_return_id: Optional[ObjectID] = None
    runtime_env: Optional[dict] = None       # validated runtime env


@dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: List[Dict[str, float]] = field(default_factory=list)
    strategy: str = "PACK"                   # PACK|SPREAD|STRICT_PACK|STRICT_SPREAD
    name: str = ""


# --------------------------------------------------------------- connection

class Connection:
    """Blocking framed-message socket with thread-safe sends."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._recv_buf = bytearray()
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 21)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 21)

    def send(self, msg: Tuple[int, Any]) -> None:
        data = pickle.dumps(msg, protocol=5)
        frame = _LEN.pack(len(data)) + data
        with self._send_lock:
            self._sock.sendall(frame)

    def recv(self) -> Optional[Tuple[int, Any]]:
        """Blocking receive of one message; None on clean EOF."""
        header = self._recv_exact(_LEN.size)
        if header is None:
            return None
        (length,) = _LEN.unpack(header)
        body = self._recv_exact(length)
        if body is None:
            return None
        return pickle.loads(body)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = self._recv_buf
        while len(buf) < n:
            try:
                chunk = self._sock.recv(max(n - len(buf), 1 << 16))
            except (ConnectionResetError, OSError):
                return None
            if not chunk:
                return None
            buf.extend(chunk)
        out = bytes(buf[:n])
        del buf[:n]
        return out

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def connect_unix(path: str, timeout: float = 30.0) -> Connection:
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect(path)
    sock.settimeout(None)
    return Connection(sock)


def connect_tcp(host: str, port: int, timeout: float = 30.0) -> Connection:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return Connection(sock)


def connect_address(address: str, timeout: float = 30.0) -> Connection:
    """Connect to ``host:port`` (TCP) or a filesystem path (unix)."""
    if ":" in address and not address.startswith("/"):
        host, port = address.rsplit(":", 1)
        return connect_tcp(host, int(port), timeout)
    return connect_unix(address, timeout)


def listen_tcp(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    """Bound+listening TCP socket; port 0 picks a free port (read it back
    via ``sock.getsockname()[1]``)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock
