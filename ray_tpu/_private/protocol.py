"""Wire protocol between drivers/workers and the node service.

Equivalent role to the reference's gRPC surface (``protobuf/core_worker.proto``,
``node_manager.proto``): task push, object status, actor control. We use
length-prefixed pickled frames over unix-domain sockets — the control plane
is local to a host; cross-host transfer rides the object plane (shm on one
host, chunked TCP between hosts in the multi-node deployment).

The transport batches and scatter-gathers (see ``Connection``): sends go
through a per-connection bounded queue drained by a writer thread that
coalesces every pending message into as few frames and ``sendmsg`` calls
as possible, and large buffers ride out-of-band as iovecs (pickle
protocol 5) instead of being copied through the pickle stream.
"""

from __future__ import annotations

import pickle
import socket
import struct
import sys
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import fieldsan
from . import locksan
from . import telemetry
from .config import CONFIG
from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID

# ----------------------------------------------------------------- opcodes
# client -> service
REGISTER = 1            # (kind, worker_id, pid)
SUBMIT_TASK = 2         # TaskSpec
CREATE_ACTOR = 3        # ActorSpec
SUBMIT_ACTOR_TASK = 4   # TaskSpec (actor_id set)
PUT_OBJECT = 5          # ObjectMeta
GET_OBJECTS = 6         # (req_id, [ObjectID], timeout_s|None)
WAIT_OBJECTS = 7        # (req_id, [ObjectID], num_returns, timeout_s)
FREE_OBJECTS = 8        # [ObjectID]
KILL_ACTOR = 9          # (ActorID, no_restart)
CANCEL_TASK = 10        # (TaskID, force)
GET_NAMED_ACTOR = 11    # (req_id, name, namespace)
# op 22 retired: SUBSCRIBE_EVENTS, superseded by GCS_SUBSCRIBE (op 36)
KV_PUT = 12             # (key, value, overwrite)
KV_GET = 13             # (req_id, key)
KV_DEL = 14             # key
KV_KEYS = 15            # (req_id, prefix)
FETCH_FUNCTION = 16     # (req_id, function_id)
CLUSTER_INFO = 17       # (req_id, what)
TASK_DONE = 18          # (task_id, [ObjectMeta], error|None, is_actor_creation)
CREATE_PG = 19          # PlacementGroupSpec
REMOVE_PG = 20          # PlacementGroupID
ACTOR_EXIT = 21         # (actor_id, reason)
STATE_QUERY = 23        # (req_id, what, filters)
PROFILE_EVENT = 24      # (kind, payload)
PUT_OBJECT_SYNC = 25    # (req_id, ObjectMeta) — acked once the store adopts it
ALLOC_OBJECT = 26       # (req_id, ObjectID, size) — arena Create; reply
                        # INFO_REPLY (arena_path, offset) | None

# node <-> node (network plane; reference analogues:
# ``node_manager.proto:363`` RequestWorkerLease/forwarding and
# ``object_manager.h:117`` Push/Pull)
NODE_POST = 27          # item tuple, enqueued on the peer's event loop
OBJ_GET_META = 28       # (req_id, ObjectID, pin) -> INFO_REPLY meta|None
OBJ_UNPIN = 29          # ObjectID
# op 30 retired: whole-payload OBJ_PULL, superseded by OBJ_PULL_CHUNK
PG_RESERVE = 31         # (req_id, pg_key, demand) -> INFO_REPLY bool
PG_RELEASE = 32         # pg_key
NODE_STATS = 33         # (req_id, what) -> INFO_REPLY payload

# client/node <-> GCS service (reference: ``gcs_service.proto:63-699``)
GCS_CALL = 34           # (req_id, method, args, kwargs) -> INFO_REPLY
GCS_CAST = 35           # (method, args, kwargs) — no reply (hot mutators)
GCS_SUBSCRIBE = 36      # channel — pushes EVENT (channel, payload) frames

# distributed reference counting (reference: ``reference_count.h:61``)
REF_REGISTER = 37       # ObjectID — this client now holds a reference
REF_DROP = 38           # ObjectID — this client's last local ref died
REF_BATCH = 39          # [(op, ObjectID), ...] — coalesced edge stream

# Cross-host driver data plane (Ray-Client-equivalent attach: the driver
# shares no /dev/shm with the cluster, so payloads ride the socket).
# Numbered after the reply range — 40-51 are already taken below.
GET_OBJECTS_FETCH = 52  # (req_id, [ObjectID], timeout) — GET_REPLY metas
                        # with shm/arena payloads converted to inline
PUT_OBJECT_WIRE = 53    # (req_id, ObjectID, bytes) — node materializes
                        # the payload in ITS store and seals

# Worker blocked in a get(): release its CPU so nested tasks can run
# (reference: NotifyDirectCallTaskBlocked/Unblocked, core_worker.cc)
NOTIFY_BLOCKED = 54     # no payload
NOTIFY_UNBLOCKED = 55   # no payload

# Chunked cross-host pull (reference: object_manager.h:117 Push/Pull in
# bounded chunks — a multi-GB object must never be one socket frame)
OBJ_PULL_CHUNK = 56     # (req_id, ObjectID, offset, length)
                        # -> INFO_REPLY (meta, bytes|None)|None

# Coalesced submission stream: [(SUBMIT_TASK|SUBMIT_ACTOR_TASK, spec),
# ...] — one frame + one dispatcher wakeup per burst (reference
# analogue: the C++ submit queue amortizing per-call overhead)
SUBMIT_BATCH = 57

# Streaming generator returns (reference: ReportGeneratorItemReturns,
# ``core_worker.proto:396``; consumer surface ``_raylet.pyx:252``
# ObjectRefGenerator)
GEN_ITEM = 58           # worker -> node: (task_id, index, ObjectMeta)
GEN_ACK = 59            # node -> worker push: (task_id, consumed_count)
GEN_NEXT = 60           # (req_id, task_id, index) -> INFO_REPLY
                        #   ("item", meta) | ("end", count)
                        #   | ("error", err_bytes)
GEN_CLOSE = 61          # (task_id,) — consumer dropped the generator
EXECUTE_BATCH = 62      # node -> worker: [EXECUTE_TASK payload, ...]
# op 63 reserved (was TASK_DONE_BATCH; DONEs leave per task so an
# early result is never withheld behind a slow batch successor —
# transport-level write coalescing now batches them without withholding)
CANCEL_QUEUED = 64      # node -> worker: task_id queued behind current
RETURN_LEASED = 65      # worker -> node: [(task_id, lease_seq)] unstarted
                        # leased tasks, each echoing its grant's seq so a
                        # stale rescue can never un-assign a newer grant
RETURN_REFS = 66        # worker -> node: (return_oid, [contained oids]) —
                        # refs pickled INSIDE a return; pinned until the
                        # return object is freed (sent before TASK_DONE)

# Distributed debugging (reference analogues: ``ray stack`` shelling
# py-spy over worker pids, and the profiling hooks). Collection fans
# out over the node plane; per-process replies ride the same conn the
# request arrived on, answered by the RECEIVER's reader thread — which
# is never the thread blocked in user code, so a worker wedged in get()
# still reports its stack.
CLUSTER_STACKS = 67     # driver -> node: (req_id, timeout_s)
                        # -> INFO_REPLY {"nodes": {...}, "groups": [...]}
CLUSTER_PROFILE = 68    # driver -> node: (req_id, opts dict)
                        # -> INFO_REPLY {"nodes": {...}, "collapsed": {...}}
STACK_DUMP = 69         # node -> worker/driver push: token
STACK_REPLY = 70        # worker/driver -> node: (token, dump dict)
PROFILE_START = 71      # node -> worker push: (token, opts dict)
PROFILE_REPORT = 72     # worker -> node: (token, report dict)

# Collective data plane (reference analogues: the ring/tree schedules of
# NCCL-backed ``util/collective`` — here the chunks ride the node plane).
# A rank addresses a peer rank by (node_id, worker_id) endpoint; its node
# routes each chunk either to a local process's conn or across the node
# plane, and payload tensors travel out-of-band (pickle-5 iovecs) on
# every hop. Handled on reader threads end to end — never the
# dispatcher — so collective traffic cannot queue behind task dispatch.
COLL_ROUTE = 74         # client -> node: (dst_node, dst_worker, key, payload)
COLL_FWD = 75           # node -> node: same body, deliver on the dst node
COLL_DELIVER = 76       # node -> client push: (key, payload) — deposited
                        # into the process mailbox (coll_transport.py)

# Collective flight-recorder progress plane (reference analogue: the
# NCCL flight recorder's dump collection). COLL_PROGRESS is pushed to
# every worker/driver conn and answered on the RECEIVER's reader thread
# — like STACK_DUMP, so a rank wedged inside a collective wait still
# reports its watermarks. CLUSTER_COLL is the driver/worker-facing
# collection op: the node fans out locally and across the node plane
# (NODE_STATS ("coll", timeout)) and replies with the aggregated
# snapshots or the diagnosed health report.
COLL_PROGRESS = 77      # node -> worker/driver push: token
COLL_PROGRESS_REPLY = 78  # worker/driver -> node: (token, snapshot dict)
CLUSTER_COLL = 79       # any client -> node: (req_id, what, timeout_s)
                        # what = "health" | "records" -> INFO_REPLY dict

# Object ownership/provenance plane (reference analogue: the
# ReferenceCounter's per-ref creation callsites behind
# RAY_record_ref_creation_sites, surfaced by `ray memory`). Clients
# buffer (oid, callsite, creator) records per put()/.remote() and flush
# them alongside the ref-edge stream; the node applies them to the
# control-plane provenance table so every object in the ledger knows
# who made it and from where.
OBJ_PROVENANCE = 80     # [(ObjectID, callsite, creator), ...]

# Checkpointable actors (reference analogue: the GCS-backed actor
# checkpointing of gcs.proto's ActorCheckpointData — state captured by
# an opt-in save_checkpoint()/restore_checkpoint(state) protocol). The
# blob lives in the CONTROL PLANE, not the checkpointing node's object
# store: a checkpoint must survive the death of the very node that
# wrote it, or a node-death restart restores nothing.
ACTOR_CHECKPOINT = 81       # (req_id, ActorID, seq, blob) -> INFO_REPLY
                            # True once the plane holds it (the worker
                            # blocks: a reported completion implies its
                            # checkpoint is durable)
ACTOR_CHECKPOINT_GET = 82   # (req_id, ActorID) -> INFO_REPLY
                            # (seq, blob) | None — replayed into a
                            # restarted actor before queued calls drain
SET_LOG_LABEL = 83          # worker -> node: label str — this worker's
                            # log lines should carry a human name (e.g.
                            # a serve replica's "deployment#tag") in
                            # the driver's "(worker ...)" prefix
                            # instead of a bare worker id

# Generic coalesced frame: (BATCH, [(op, payload), ...]). Produced by
# the Connection writer when several messages are pending at flush time
# — ONE pickle stream + one frame + one receiver wakeup for the burst —
# and expanded transparently by the Connection decoder, so dispatch
# code never sees it. Unlike SUBMIT_BATCH (a scheduler-level op with
# one-dispatch-pass semantics) this is pure transport.
BATCH = 73

# service -> client
EXECUTE_TASK = 40       # (kind, TaskSpec, resolved deps, ActorSpec|None,
                        # lease_seq) — seq names this grant in the
                        # sequenced lease handshake (0 for actor calls)
GET_REPLY = 41          # (req_id, [ObjectMeta])
WAIT_REPLY = 42         # (req_id, [ready ObjectID], [pending ObjectID])
NAMED_ACTOR_REPLY = 43  # (req_id, actor_info | None)
KV_REPLY = 44           # (req_id, value)
FUNCTION_REPLY = 45     # (req_id, blob | None)
INFO_REPLY = 46         # (req_id, payload)
# op 47 retired: ACTOR_STATE pushes, superseded by the GCS "ACTOR"
# pubsub channel (EVENT frames)
SHUTDOWN = 48           # ()
EVENT = 49              # (channel, payload)
ERROR_REPLY = 50        # (req_id, pickled exception)
PUT_REPLY = 51          # (req_id,)

KIND_DRIVER = 0
KIND_WORKER = 1


# ------------------------------------------------------------------- specs

def _mk_task_spec(t: tuple) -> "TaskSpec":
    """Rebuild a TaskSpec from its flattened wire tuple (see
    ``TaskSpec.__reduce__``). Positional layout == dataclass field
    order; __new__ + direct assignment skips __init__ overhead."""
    s = TaskSpec.__new__(TaskSpec)
    (tid, jid, s.name, s.function_id, s.args, s.kwargs, s.num_returns,
     rids, s.resources, s.max_retries, s.retry_exceptions, aid,
     s.method_name, s.seq_no, s.scheduling_strategy, s.owner_id,
     s.origin_node_id, s.namespace, s.runtime_env, s.trace_context,
     s.accel_ids, s.request_ctx) = t
    s.task_id = TaskID(tid)
    s.job_id = JobID(jid)
    s.return_ids = [ObjectID(b) for b in rids]
    s.actor_id = ActorID(aid) if aid is not None else None
    return s


@dataclass
class TaskSpec:
    """Immutable description of a task invocation.

    Reference analogue: ``TaskSpecification``
    (``src/ray/common/task/task_spec.h:244``).
    """

    task_id: TaskID
    job_id: JobID
    name: str
    function_id: bytes                       # content hash of the pickled fn
    # each arg: ("v", wire_bytes) inline value | ("r", ObjectID) reference
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs: Dict[str, Tuple[str, Any]] = field(default_factory=dict)
    num_returns: int = 1
    return_ids: List[ObjectID] = field(default_factory=list)
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    # actor-related
    actor_id: Optional[ActorID] = None       # set for actor method calls
    method_name: str = ""
    seq_no: int = 0                          # actor call ordering
    # scheduling
    scheduling_strategy: Any = None          # None | "SPREAD" | NodeAffinity | PG
    owner_id: bytes = b""                    # WorkerID binary of the submitter
    # NodeID binary of the node that owns/routes this task; a starved
    # target spills the task back here for re-routing (reference
    # analogue: lease spillback keeps the owner in the loop)
    origin_node_id: bytes = b""
    namespace: str = "default"               # submitter's job namespace
    runtime_env: Optional[dict] = None       # validated runtime env
    # tracing: caller's (trace_id, span_id), propagated into the worker
    # (reference: ray.util.tracing traceparent in the task spec)
    trace_context: Optional[dict] = None
    # per-instance accelerator slots assigned by the executing node at
    # dispatch (reference: resource-instance ids / GPU id assignment);
    # read via get_runtime_context().get_accelerator_ids()
    accel_ids: Optional[List[int]] = None
    # request-scoped baggage (serve request ids; reference analogue:
    # W3C baggage): submitter's context.request_ctx tuple, re-bound by
    # the executing worker so the request's whole call tree carries it
    request_ctx: Optional[tuple] = None

    def __reduce__(self):
        # Hot-path serialization: a task spec crosses the wire 2-3 times
        # per invocation (submit, dispatch, peer forward). The default
        # dataclass pickle costs ~28us/spec (per-object reduce of every
        # ID); flattening to one tuple with IDs as raw bytes is ~8us.
        # tests/test_core_basic.py::test_spec_wire_roundtrip guards the
        # field list against drift.
        return (_mk_task_spec, (
            (self.task_id.binary(), self.job_id.binary(), self.name,
             self.function_id, self.args, self.kwargs, self.num_returns,
             [r.binary() for r in self.return_ids], self.resources,
             self.max_retries, self.retry_exceptions,
             self.actor_id.binary() if self.actor_id else None,
             self.method_name, self.seq_no, self.scheduling_strategy,
             self.owner_id, self.origin_node_id, self.namespace,
             self.runtime_env, self.trace_context, self.accel_ids,
             self.request_ctx),))


@dataclass
class ActorSpec:
    """Actor creation description (reference: actor creation TaskSpec +
    ``gcs_actor_manager.h:281`` registration payload)."""

    actor_id: ActorID
    job_id: JobID
    name: str                                # class name for display
    registered_name: Optional[str] = None    # named-actor name
    namespace: str = "default"
    class_blob: bytes = b""                  # cloudpickled class
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs: Dict[str, Tuple[str, Any]] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_concurrency: int = 1
    is_async: bool = False
    lifetime: Optional[str] = None           # None | "detached"
    scheduling_strategy: Any = None
    creation_return_id: Optional[ObjectID] = None
    runtime_env: Optional[dict] = None       # validated runtime env


@dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: List[Dict[str, float]] = field(default_factory=list)
    strategy: str = "PACK"                   # PACK|SPREAD|STRICT_PACK|STRICT_SPREAD
    name: str = ""


# --------------------------------------------------------------- connection
#
# Wire framing (v2):
#
#     frame := <u32 len> <u8 tag> <payload>       (len counts tag+payload)
#
#     tag 0 (plain): payload = pickle-5 stream, buffers in-band
#     tag 1 (oob):   payload = <u32 pkl_len> <u32 nbuf> <u64 len_0..n-1>
#                              <pickle stream> <buf_0> ... <buf_n-1>
#
# A tag-1 frame carries pickle protocol-5 out-of-band buffers: any
# ``PickleBuffer`` (and any buffer-protocol object that opts into
# protocol-5 out-of-band pickling, e.g. contiguous numpy arrays) of
# ``transport_oob_threshold_bytes`` or more is shipped as a raw iovec
# after the pickle stream instead of being copied into it. The decoder
# hands ``pickle.loads`` zero-copy memoryviews into the frame buffer,
# so a large payload is copied exactly once (socket -> frame buffer)
# before landing at its destination (arena block / shm segment).

_TAG_PLAIN = 0
_TAG_OOB = 1
_HDR = struct.Struct("<IB")          # frame length + tag
_OOB_HDR = struct.Struct("<II")      # pickle_len, nbuf
_U64 = struct.Struct("<Q")

# frames at/above this size are received into a dedicated buffer filled
# straight off the socket: one copy, and out-of-band views into it stay
# valid without a second materialization
_DEDICATED_RECV_MIN = 1 << 16
# single-frame sends up to this body size concatenate header+body and
# use one plain send: a sub-µs copy beats the extra-iovec sendmsg cost;
# bigger bodies ride as iovecs (the copy the old transport paid on
# EVERY frame is what this replaces)
_SMALL_CONCAT_MAX = 1 << 12
# iovecs per sendmsg call (IOV_MAX is 1024 on Linux; stay well under)
_MAX_IOV = 512
# close() flushes queued frames for at most this long before cutting
# the socket (a wedged peer must not hang teardown)
_CLOSE_DRAIN_TIMEOUT = 5.0


def oob_wrap(data):
    """Wrap a bytes-like payload so the transport ships it out-of-band
    (zero-copy iovec) when it clears the threshold; small payloads stay
    plain. The receiver sees a memoryview for wrapped payloads."""
    if data is not None and len(data) >= CONFIG.transport_oob_threshold_bytes:
        return pickle.PickleBuffer(data)
    return data


def fail_dropped_request(msg, exc: BaseException, lock, futures) -> None:
    """Shared ``Connection.on_send_error`` body for request/reply
    channels: when the transport drops a queued frame (encode failure
    on the drainer path), fail the pending future whose req_id the
    frame carried instead of letting its caller block forever.
    Requests are ``(op, (req_id, ...))`` by construction at every
    call site."""
    try:
        payload = msg[1]
        req_id = payload[0] if type(payload) is tuple and payload else None
    except Exception:
        return
    if not isinstance(req_id, int):
        return
    with lock:
        fut = futures.pop(req_id, None)
    if fut is not None and not fut.done():
        fut.set_exception(
            exc if isinstance(exc, Exception) else RuntimeError(str(exc)))


def _est_size(payload, depth: int = 3) -> int:
    """Cheap pre-pickle size estimate used to bound batch frames. Exact
    for the dominant large carriers (bytes-like leaves, PickleBuffers
    and ObjectMeta inlines); everything else counts a small constant.
    Depth 3 reaches the hottest shapes' payloads — a TASK_DONE /
    GET_REPLY message is ``(op, (id, [metas], ...))``, putting the
    metas three levels down. Long lists are sampled (first 16) and
    extrapolated so a burst of meta-carrying replies still respects
    ``transport_max_batch_bytes``."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return len(payload) + 32
    if isinstance(payload, pickle.PickleBuffer):
        try:
            return payload.raw().nbytes + 32
        except Exception:
            return 64
    if depth > 0 and isinstance(payload, (tuple, list)):
        n = len(payload)
        if n == 0:
            return 32
        est = sum(_est_size(v, depth - 1) for v in payload[:16])
        if n > 16:
            est = est * n // 16
        return 32 + est
    inline = getattr(payload, "inline", None)
    if inline is not None:
        return len(inline) + 128
    # numpy arrays (collective chunks) expose nbytes; without this a
    # burst of 512KB chunks would estimate as 64B each and coalesce
    # into one multi-MB BATCH frame
    nbytes = getattr(payload, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes + 64
    return 64


@fieldsan.guarded
class Connection:
    """Framed-message socket: batched, vectored, thread-safe sends
    through a per-connection writer thread; burst receives.

    ``send`` enqueues and returns; a lazily-started writer drains the
    queue the moment it is non-empty (opportunistic corking — no
    latency timers), packing every pending message into as few frames
    (small ones coalesce into one ``BATCH``) and as few ``sendmsg``
    syscalls as possible. The receive side decodes every complete frame
    per socket wakeup, so ``recv_many`` hands the dispatcher a whole
    burst at once.
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._sendmsg = getattr(sock, "sendmsg", None)
        self._qlock = locksan.lock("conn.queue")    # guards _outq + flags
        self._flush_lock = locksan.lock("conn.flush")  # the active drainer
        self._outq: "deque" = deque()
        self._broken = False            # socket died under a drainer
        self._closing = False
        self._recv_buf = bytearray()
        self._decoded: "deque" = deque()
        self._max_batch_msgs = max(1, CONFIG.transport_max_batch_msgs)
        self._max_batch_bytes = max(1 << 12, CONFIG.transport_max_batch_bytes)
        self._queue_depth = max(1, CONFIG.transport_queue_depth)
        self._oob_threshold = max(1, CONFIG.transport_oob_threshold_bytes)
        # flush stats, accumulated as plain ints on the (single-drainer)
        # flush path and published to telemetry every 64 flushes — the
        # single-message fast path must not pay shard locks per frame
        self._stat_flushes = 0
        self._stat_msgs = 0
        self._stat_bytes = 0
        self._stat_oob = 0
        # out-of-band views collected by _buffer_cb during one encode;
        # safe as instance state because encoding only happens under
        # _flush_lock (single drainer)
        self._oob_scratch: List[memoryview] = []
        # called with (msg, exc) when a queued message is dropped on the
        # drainer path (encode failure that cannot be raised to its
        # sender) — request/reply channels hook this to fail the pending
        # future the dropped request would otherwise hang forever
        self.on_send_error = None
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                            CONFIG.socket_send_buffer_bytes)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                            CONFIG.socket_recv_buffer_bytes)
        except OSError:
            pass

    # ------------------------------------------------------------- sending
    #
    # Combining drainer ("the writer"): the sending thread enqueues and
    # then tries to become the drainer. Uncontended sends go straight to
    # the socket with no handoff; when another thread is already mid-
    # ``sendmsg``, messages pile onto the queue and the active drainer
    # picks them ALL up in one coalesced batch before releasing — the
    # burst pays one pickle header + one syscall. Opportunistic corking
    # with zero added latency (no timers, no thread hop).

    def send(self, msg: Tuple[int, Any]) -> None:
        """Send one message. Uncontended sends (no active drainer,
        empty queue — the overwhelmingly common case) encode and write
        inline with zero queue/wakeup bookkeeping, so batching costs
        nothing when there is nothing to batch."""
        if not self._outq and self._flush_lock.acquire(blocking=False):
            try:
                # benign unlocked read: the flags only ever flip to
                # True, and a send that slips past lands on a dead
                # socket and raises from sendmsg anyway
                if self._broken or self._closing:
                    raise OSError("connection is closed")
                try:
                    self._send_one(msg, reraise=True)
                except (OSError, ValueError):
                    self._poison()
                    raise
                finally:
                    # strand-guard: a contended producer that saw our
                    # lock held returned expecting us to pick its
                    # messages up — runs even when OUR encode failed
                    # (the connection is healthy then; a poisoned
                    # socket cleared the queue already)
                    if not self._broken:
                        self._drain_holding()
            finally:
                self._flush_lock.release()
                if self._outq:
                    # an enqueue slipped in after our final check but
                    # before the release — drain it like any other
                    # producer
                    self._drain()
            return
        self._enqueue((msg,))
        self._drain()

    def send_many(self, msgs) -> None:
        """Queue several messages as one ordered burst, then flush."""
        if msgs:
            self._enqueue(tuple(msgs))
            self._drain()

    def send_lazy(self, msg: Tuple[int, Any]) -> None:
        """Enqueue WITHOUT draining: the message leaves on the next
        ``send``/``send_many``/``kick``/``flush`` (any of which drains
        the whole queue in order). Lets a sender coalesce a frame with
        ones it knows are coming — the caller owns bounding the hold
        (e.g. the worker's TASK_DONE kicker)."""
        self._enqueue((msg,))

    def kick(self) -> None:
        """Drain anything queued (no-op when empty): the flush half of
        ``send_lazy``."""
        self._drain()

    def _enqueue(self, msgs: tuple) -> None:
        with self._qlock:
            if self._broken or self._closing:
                raise OSError("connection is closed")
            self._outq.extend(msgs)
            over = len(self._outq) > self._queue_depth
        if over:
            # bounded queue: the producer becomes/waits-for the drainer
            # until the backlog is gone (a streamed multi-GB pull must
            # not buffer unbounded frames in memory)
            telemetry.counter_inc(telemetry.M_TRANSPORT_QUEUE_STALLS)
            self._drain(block=True)

    def _poison(self) -> None:
        """Socket died under a drainer: drop the backlog and poison
        future sends; the peer-death signal is the reader's EOF."""
        with self._qlock:
            self._broken = True
            self._outq.clear()

    def _drain_holding(self) -> None:
        """Drain the queue to empty. Caller holds ``_flush_lock``."""
        while self._outq:       # unlocked peek: misses are caught by
            with self._qlock:   # the post-release re-check in send()
                batch = list(self._outq)
                self._outq.clear()
                if not batch:
                    return
            try:
                self._write_batch(batch)
            except (OSError, ValueError):
                self._poison()
                raise

    def _drain(self, block: bool = False) -> None:
        while True:
            if not self._outq:
                return
            if not self._flush_lock.acquire(blocking=block):
                # an active drainer exists; it re-checks the queue after
                # releasing, so our messages cannot be stranded — but if
                # they are still queued once it released, loop and drain
                # them ourselves (covers the enqueue-after-final-check
                # race)
                if not block:
                    with self._qlock:
                        if self._outq and not self._flush_lock.locked():
                            continue
                    return
                continue
            try:
                self._drain_holding()
            finally:
                self._flush_lock.release()

    def flush(self, timeout: Optional[float] = 5.0) -> None:
        """Block until every message enqueued before this call reached
        the socket (or the connection died / the timeout expired).

        The delivery guarantee is ACQUIRING ``_flush_lock``, not
        observing an empty queue: a batch another drainer popped before
        this call only counts as written once that drainer releases —
        an empty ``_outq`` alone says nothing about frames mid-
        ``sendmsg`` in a foreign drainer."""
        deadline = (time.monotonic() + timeout) if timeout else None
        while not self._broken:
            remaining = 0.1
            if deadline is not None:
                remaining = min(0.1, deadline - time.monotonic())
                if remaining <= 0:
                    return
            if self._flush_lock.acquire(timeout=remaining):
                try:
                    try:
                        self._drain_holding()
                    except OSError:
                        return
                finally:
                    self._flush_lock.release()
                if not self._outq:
                    return

    def _send_one(self, msg, reraise: bool = False) -> None:
        """Single-message fast path: encode + vectored write with
        minimal bookkeeping (no chunk list, no grouping pass).

        ``reraise`` propagates encode failures to the caller — the
        uncontended ``send()`` path, where the sender is the thread
        that owns the message and a dropped frame would leave a
        request-reply future unresolved forever. The drainer/batch
        path keeps drop-with-traceback: there the encoding thread may
        not be the sender, and one bad payload must not poison the
        connection."""
        try:
            body = pickle.dumps(msg, protocol=5,
                                buffer_callback=self._buffer_cb)
        except Exception as exc:
            self._oob_scratch.clear()
            if reraise:
                raise
            self._drop_msg(msg, exc)
            return
        if self._oob_scratch:
            chunks: list = []
            oob = self._oob_frame(body, chunks)
            self._account(1, chunks, oob)
            self._sendv(chunks)
            return
        nbody = len(body)
        total = _HDR.size + nbody
        self._stat_flushes += 1
        self._stat_msgs += 1
        self._stat_bytes += total
        if self._stat_flushes >= 64:
            self._publish_stats()
        hdr = _HDR.pack(1 + nbody, _TAG_PLAIN)
        sendmsg = self._sendmsg
        if nbody <= _SMALL_CONCAT_MAX or sendmsg is None:
            self._sock.sendall(hdr + body)
            return
        sent = sendmsg((hdr, body))
        if sent < total:
            self._finish_partial([hdr, body], sent, total, sendmsg)

    def _drop_msg(self, msg, exc: BaseException) -> None:
        """A queued message failed to encode on the drainer path and
        cannot be raised to its sender (the drainer may be a different
        thread): log it, and give the owning channel a chance to fail
        the pending future a dropped request would otherwise hang."""
        traceback.print_exc(file=sys.stderr)
        cb = self.on_send_error
        if cb is not None:
            try:
                cb(msg, exc)
            except Exception:
                pass

    def _write_batch(self, batch: list) -> None:
        if len(batch) == 1:
            self._send_one(batch[0])
            return
        chunks = []
        oob_bytes = 0
        group: list = []
        group_est = 0

        def emit_group():
            nonlocal group, group_est, oob_bytes
            if not group:
                return
            msg = group[0] if len(group) == 1 else (BATCH, group)
            try:
                oob_bytes += self._encode_frame(msg, chunks)
            except Exception as exc:
                # one unpicklable payload must not poison its batchmates
                # (or the connection): retry one by one, dropping the
                # offender with a traceback + on_send_error
                if len(group) > 1:
                    for one in group:
                        try:
                            oob_bytes += self._encode_frame(one, chunks)
                        except Exception as one_exc:
                            self._drop_msg(one, one_exc)
                else:
                    self._drop_msg(group[0], exc)
            group = []
            group_est = 0

        for msg in batch:
            est = _est_size(msg)
            if group and (len(group) >= self._max_batch_msgs
                          or group_est + est > self._max_batch_bytes):
                emit_group()
            group.append(msg)
            group_est += est
        emit_group()
        if not chunks:
            return
        # a coalesced flush is the interesting signal: record it exactly
        telemetry.hist_observe(telemetry.M_TRANSPORT_FLUSH_FRAMES,
                               float(len(batch)))
        self._account(len(batch), chunks, oob_bytes)
        self._sendv(chunks)

    def _account(self, n_msgs: int, chunks: list, oob_bytes: int) -> None:
        """Accumulate flush stats as plain ints (we are the only
        drainer); publish to telemetry every 64 flushes."""
        self._stat_flushes += 1
        self._stat_msgs += n_msgs
        self._stat_bytes += sum(len(c) for c in chunks)
        self._stat_oob += oob_bytes
        if self._stat_flushes >= 64:
            self._publish_stats()

    def _publish_stats(self) -> None:
        flushes, msgs = self._stat_flushes, self._stat_msgs
        nbytes, oob = self._stat_bytes, self._stat_oob
        self._stat_flushes = self._stat_msgs = 0
        self._stat_bytes = self._stat_oob = 0
        telemetry.counter_inc(telemetry.M_TRANSPORT_SEND_BYTES,
                              float(nbytes))
        if oob:
            telemetry.counter_inc(telemetry.M_TRANSPORT_OOB_BYTES,
                                  float(oob))
        if msgs == flushes:
            # all-singles window: one aggregate observation keeps the
            # frames-per-flush histogram honest about uncoalesced load
            # without paying a shard lock per frame
            telemetry.hist_observe(telemetry.M_TRANSPORT_FLUSH_FRAMES, 1.0)

    def _buffer_cb(self, pb) -> bool:
        """pickle-5 buffer_callback: large contiguous buffers collect
        into _oob_scratch to ride out-of-band (bound method — no
        closure allocation per frame)."""
        try:
            view = pb.raw()
        except Exception:               # non-contiguous: in-band copy
            return True
        if view.nbytes < self._oob_threshold:
            return True                 # truthy => keep in-band
        self._oob_scratch.append(view)
        return False                    # falsy => ship out-of-band

    def _encode_frame(self, msg, chunks: list) -> int:
        """Append one frame's iovec chunks; returns out-of-band bytes."""
        try:
            body = pickle.dumps(msg, protocol=5,
                                buffer_callback=self._buffer_cb)
        except Exception:
            self._oob_scratch.clear()
            raise
        if not self._oob_scratch:
            chunks.append(_HDR.pack(1 + len(body), _TAG_PLAIN))
            chunks.append(body)
            return 0
        return self._oob_frame(body, chunks)

    def _oob_frame(self, body: bytes, chunks: list) -> int:
        """Append a tag-1 frame carrying _oob_scratch as iovecs."""
        buffers = list(self._oob_scratch)
        self._oob_scratch.clear()
        oob = 0
        lens = bytearray()
        for v in buffers:
            lens += _U64.pack(v.nbytes)
            oob += v.nbytes
        total = 1 + _OOB_HDR.size + len(lens) + len(body) + oob
        chunks.append(_HDR.pack(total, _TAG_OOB)
                      + _OOB_HDR.pack(len(body), len(buffers)) + lens)
        chunks.append(body)
        chunks.extend(buffers)
        return oob

    def _sendv(self, chunks: list) -> None:
        """Vectored send of every chunk, handling partial writes."""
        sendmsg = self._sendmsg
        if sendmsg is None:             # pragma: no cover - non-Linux
            for c in chunks:
                self._sock.sendall(c)
            return
        i = 0
        n = len(chunks)
        while i < n:
            if i == 0 and n <= _MAX_IOV:
                group = chunks          # common case: no slice copy
            else:
                group = chunks[i:i + _MAX_IOV]
            i += len(group)
            total = sum(len(c) for c in group)
            sent = sendmsg(group)
            if sent < total:
                self._finish_partial(list(group), sent, total, sendmsg)

    @staticmethod
    def _finish_partial(group: list, sent: int, total: int,
                        sendmsg) -> None:
        """Resend the unsent tail after a short ``sendmsg`` (kernel
        buffer filled mid-frame)."""
        while sent < total:
            total -= sent
            j = 0
            while sent >= len(group[j]):
                sent -= len(group[j])
                j += 1
            if sent:
                group = [memoryview(group[j])[sent:]] + group[j + 1:]
            else:
                group = group[j:]
            sent = sendmsg(group)

    # ----------------------------------------------------------- receiving
    def recv(self) -> Optional[Tuple[int, Any]]:
        """Blocking receive of one message; None on EOF."""
        if not self._decoded and not self._fill_decoded():
            return None
        return self._decoded.popleft()

    def recv_many(self) -> Optional[List[Tuple[int, Any]]]:
        """Blocking receive of every already-decodable message (>= 1);
        None on EOF. One socket wakeup hands the caller a whole burst."""
        if not self._decoded and not self._fill_decoded():
            return None
        out = list(self._decoded)
        self._decoded.clear()
        if len(out) > 1:
            telemetry.hist_observe(telemetry.M_TRANSPORT_RECV_FRAMES,
                                   float(len(out)))
        return out

    def _fill_decoded(self) -> bool:
        """Read + decode until at least one message is ready. All frames
        already buffered decode in one pass (multi-frame decoder)."""
        out = self._decoded
        rb = self._recv_buf
        while not out:
            # decode every complete frame in the shared buffer; compact
            # once per pass instead of copying per read
            pos = 0
            end = len(rb)
            if end >= _HDR.size:
                mv = memoryview(rb)
                try:
                    while end - pos >= _HDR.size:
                        length, tag = _HDR.unpack_from(rb, pos)
                        if length < 1:
                            return False        # corrupt stream
                        if end - pos - _HDR.size < length - 1:
                            break
                        body = mv[pos + _HDR.size:
                                  pos + _HDR.size + length - 1]
                        try:
                            self._decode_body(tag, body, out, owned=False)
                        finally:
                            body.release()
                        pos += _HDR.size + length - 1
                finally:
                    mv.release()
                if pos:
                    del rb[:pos]
                    if out:
                        return True
            # a large incomplete frame is read straight into a dedicated
            # buffer: one copy off the socket, and out-of-band views
            # into it stay valid with no re-materialization
            if len(rb) >= _HDR.size:
                length, tag = _HDR.unpack_from(rb, 0)
                if length - 1 >= _DEDICATED_RECV_MIN:
                    body = bytearray(length - 1)
                    have = len(rb) - _HDR.size
                    body[:have] = memoryview(rb)[_HDR.size:]
                    del rb[:]
                    if not self._recv_into(memoryview(body)[have:]):
                        return False
                    self._decode_body(tag, memoryview(body), out,
                                      owned=True)
                    continue
            try:
                # modest read size: CPython allocates the full bufsize
                # per recv() call, so a large constant here pays an
                # allocation + page-fault tax on every wakeup
                chunk = self._sock.recv(1 << 16)
            except (ConnectionResetError, OSError):
                return False
            if not chunk:
                return False
            rb += chunk
        return True

    def _recv_into(self, view: memoryview) -> bool:
        while view.nbytes:
            try:
                n = self._sock.recv_into(view)
            except (ConnectionResetError, OSError):
                return False
            if not n:
                return False
            view = view[n:]
        return True

    def _decode_body(self, tag: int, body: memoryview, out: deque,
                     owned: bool) -> None:
        if tag == _TAG_PLAIN:
            msg = pickle.loads(body)
        else:
            if not owned:
                # out-of-band views must outlive the shared recv buffer
                body = memoryview(bytearray(body))
            pkl_len, nbuf = _OOB_HDR.unpack_from(body, 0)
            off = _OOB_HDR.size + nbuf * _U64.size
            pkl = body[off:off + pkl_len]
            off += pkl_len
            bufs = []
            for i in range(nbuf):
                (blen,) = _U64.unpack_from(body,
                                           _OOB_HDR.size + i * _U64.size)
                bufs.append(body[off:off + blen])
                off += blen
            msg = pickle.loads(pkl, buffers=bufs)
        if type(msg) is tuple and msg and msg[0] == BATCH:
            out.extend(msg[1])
        else:
            out.append(msg)

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        with self._qlock:
            self._closing = True
        # drain what was queued before the close (a side-effecting frame
        # sent just before shutdown must still reach the peer) — but
        # bounded: a wedged peer that stopped reading leaves the socket
        # buffer full, and teardown must not hang on it. The shutdown()
        # below also errors out a foreign drainer stuck mid-send, which
        # is what unblocked a stuck sendall in the pre-batching
        # transport.
        try:
            self._sock.settimeout(_CLOSE_DRAIN_TIMEOUT)
        except OSError:
            pass
        if self._flush_lock.acquire(timeout=_CLOSE_DRAIN_TIMEOUT):
            try:
                self._drain_holding()
            except OSError:
                pass
            finally:
                self._flush_lock.release()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def connect_unix(path: str, timeout: float = 30.0) -> Connection:
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect(path)
    sock.settimeout(None)
    return Connection(sock)


def connect_tcp(host: str, port: int, timeout: float = 30.0) -> Connection:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return Connection(sock)


def connect_address(address: str, timeout: float = 30.0) -> Connection:
    """Connect to ``host:port`` (TCP) or a filesystem path (unix)."""
    if ":" in address and not address.startswith("/"):
        host, port = address.rsplit(":", 1)
        return connect_tcp(host, int(port), timeout)
    return connect_unix(address, timeout)


def listen_tcp(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    """Bound+listening TCP socket; port 0 picks a free port (read it back
    via ``sock.getsockname()[1]``)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock
