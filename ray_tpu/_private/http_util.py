"""Shared scaffold for the head node's HTTP surfaces (job REST,
dashboard). One place for JSON plumbing and server lifecycle so fixes
don't have to be made per-module."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class JsonHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):   # quiet
        pass

    def _json(self, code: int, payload,
              headers: Optional[dict] = None) -> None:
        # default=str: handler results may carry numpy scalars/bytes —
        # stringify rather than turning a good reply into a 500
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)

    def _html(self, body: str) -> None:
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(n) or b"{}")


class HttpServerBase:
    """ThreadingHTTPServer wrapper with a non-leaking stop()."""

    thread_name = "rtpu-http"

    def __init__(self, handler_cls, host: str = "0.0.0.0", port: int = 0,
                 **handler_attrs):
        handler = type("BoundHandler", (handler_cls,), handler_attrs)
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=self.thread_name, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        # shutdown() only stops serve_forever; the listening socket (and
        # its fd/port) stays bound until close
        self._httpd.server_close()
