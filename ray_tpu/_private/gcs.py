"""Control plane: cluster membership, actor/job/PG registries, KV, directory.

Equivalent role to the reference's GCS server (``src/ray/gcs/gcs_server/`` —
GcsNodeManager, GcsActorManager, GcsPlacementGroupManager, GcsKVManager,
GcsTaskManager) plus the ownership-based object directory
(``object_manager/ownership_based_object_directory.h``). In this build the
control plane is an in-process, thread-safe object: on a single host it is
embedded in the node service; an in-process multi-node cluster
(``ray_tpu.cluster_utils.Cluster``) shares one instance between node
services, mirroring the reference's single-GCS topology. Cross-host
deployment puts this behind the same framed-socket RPC used everywhere else.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .config import CONFIG
from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID
from .object_store import ObjectMeta
from .protocol import ActorSpec, PlacementGroupSpec

# Actor lifecycle states (reference: gcs.proto ActorTableData.ActorState)
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"

PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str                      # unix socket path OR "host:port" (TCP)
    resources_total: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    # in-process shortcut to the NodeService (same-process multi-node cluster)
    service: Any = None
    # OS-host identity: node processes on one host share /dev/shm, so
    # same-host peers exchange objects zero-copy by shm name while
    # cross-host peers pull payload bytes (reference: local plasma vs
    # ``object_manager.h:117`` chunked Push/Pull)
    host: str = ""
    # availability reported with heartbeats (RaySyncer-equivalent resource
    # gossip for nodes the scheduler can't snapshot in-process)
    resources_available: Dict[str, float] = field(default_factory=dict)

    def __getstate__(self):
        # the live service object never crosses the wire
        state = dict(self.__dict__)
        state["service"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)


@dataclass
class ActorRecord:
    spec: ActorSpec
    state: str = ACTOR_PENDING
    node_id: Optional[NodeID] = None
    num_restarts: int = 0
    death_reason: str = ""


@dataclass
class JobRecord:
    job_id: JobID
    driver_pid: int
    start_time: float
    end_time: Optional[float] = None


@dataclass
class TaskEvent:
    """One task state transition, kept in a bounded ring for the state API
    (reference: ``GcsTaskManager``, ``gcs_task_manager.h:61``)."""

    task_id: TaskID
    name: str
    state: str
    node_id: Optional[NodeID]
    timestamp: float
    is_actor_task: bool = False


class GlobalControlPlane:
    """Thread-safe cluster-wide registries."""

    def __init__(self):
        self._lock = threading.RLock()
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.jobs: Dict[JobID, JobRecord] = {}
        self.kv: Dict[bytes, bytes] = {}
        self.placement_groups: Dict[PlacementGroupID, dict] = {}
        # object directory: object -> (node_id, meta)
        self.directory: Dict[ObjectID, Tuple[NodeID, ObjectMeta]] = {}
        self.task_events: deque = deque(maxlen=CONFIG.task_events_buffer_size)
        self._subscribers: Dict[str, List[Callable[[Any], None]]] = {}

    # ------------------------------------------------------------- nodes
    def register_node(self, info: NodeInfo) -> None:
        # re-stamp on OUR clock: a remote registrant's monotonic stamp is
        # incomparable with this host's and could instantly trip the
        # heartbeat sweeper
        info.last_heartbeat = time.monotonic()
        with self._lock:
            self.nodes[info.node_id] = info
        self.publish("NODE", {"node_id": info.node_id, "state": "ALIVE"})

    def remove_node(self, node_id: NodeID, reason: str = "") -> None:
        dead_actors: List[ActorID] = []
        with self._lock:
            info = self.nodes.get(node_id)
            if info is None:
                return
            info.alive = False
            # drop directory entries whose only location was this node
            lost = [oid for oid, (nid, _) in self.directory.items()
                    if nid == node_id]
            for oid in lost:
                del self.directory[oid]
            for aid, rec in self.actors.items():
                if rec.node_id == node_id and rec.state != ACTOR_DEAD:
                    dead_actors.append(aid)
        self.publish("NODE", {"node_id": node_id, "state": "DEAD",
                              "reason": reason})
        for aid in dead_actors:
            self.set_actor_state(aid, ACTOR_DEAD,
                                 reason=f"node {node_id} died")

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    def heartbeat(self, node_id: NodeID,
                  resources_available: Optional[Dict[str, float]] = None
                  ) -> None:
        with self._lock:
            info = self.nodes.get(node_id)
            if info:
                info.last_heartbeat = time.monotonic()
                if resources_available is not None:
                    info.resources_available = resources_available

    def get_node(self, node_id: NodeID) -> Optional[NodeInfo]:
        with self._lock:
            return self.nodes.get(node_id)

    def nodes_snapshot(self) -> List[NodeInfo]:
        with self._lock:
            return list(self.nodes.values())

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.alive_nodes():
            for k, v in n.resources_total.items():
                out[k] = out.get(k, 0.0) + v
        return out

    # ------------------------------------------------------------ actors
    def register_actor(self, spec: ActorSpec) -> ActorRecord:
        rec = ActorRecord(spec=spec)
        with self._lock:
            if spec.registered_name:
                key = (spec.namespace, spec.registered_name)
                if key in self.named_actors:
                    raise ValueError(
                        f"actor name {spec.registered_name!r} already taken "
                        f"in namespace {spec.namespace!r}")
                self.named_actors[key] = spec.actor_id
            self.actors[spec.actor_id] = rec
        return rec

    def set_actor_state(self, actor_id: ActorID, state: str,
                        node_id: Optional[NodeID] = None,
                        reason: str = "") -> None:
        with self._lock:
            rec = self.actors.get(actor_id)
            if rec is None:
                return
            rec.state = state
            if node_id is not None:
                rec.node_id = node_id
            if reason:
                rec.death_reason = reason
            if state == ACTOR_DEAD and rec.spec.registered_name:
                self.named_actors.pop(
                    (rec.spec.namespace, rec.spec.registered_name), None)
        self.publish("ACTOR", {"actor_id": actor_id, "state": state,
                               "reason": reason})

    def get_actor(self, actor_id: ActorID) -> Optional[ActorRecord]:
        with self._lock:
            return self.actors.get(actor_id)

    def lookup_named_actor(self, name: str,
                           namespace: str = "default") -> Optional[ActorRecord]:
        with self._lock:
            actor_id = self.named_actors.get((namespace, name))
            return self.actors.get(actor_id) if actor_id else None

    # -------------------------------------------------------------- jobs
    def register_job(self, rec: JobRecord) -> None:
        with self._lock:
            self.jobs[rec.job_id] = rec

    def finish_job(self, job_id: JobID) -> None:
        with self._lock:
            rec = self.jobs.get(job_id)
            if rec:
                rec.end_time = time.time()

    # ---------------------------------------------------------------- kv
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        with self._lock:
            if not overwrite and key in self.kv:
                return False
            self.kv[key] = value
            return True

    def kv_get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(key)

    def kv_del(self, key: bytes) -> None:
        with self._lock:
            self.kv.pop(key, None)

    def kv_keys(self, prefix: bytes) -> List[bytes]:
        with self._lock:
            return [k for k in self.kv if k.startswith(prefix)]

    # ---------------------------------------------------------- directory
    def publish_location(self, object_id: ObjectID, node_id: NodeID,
                         meta: ObjectMeta) -> None:
        with self._lock:
            self.directory[object_id] = (node_id, meta)

    def lookup_location(
            self, object_id: ObjectID) -> Optional[Tuple[NodeID, ObjectMeta]]:
        with self._lock:
            return self.directory.get(object_id)

    def drop_location(self, object_id: ObjectID) -> None:
        with self._lock:
            self.directory.pop(object_id, None)

    # ----------------------------------------------------- placement groups
    def register_pg(self, spec: PlacementGroupSpec,
                    assignment: List[NodeID]) -> None:
        with self._lock:
            self.placement_groups[spec.pg_id] = {
                "spec": spec, "state": PG_CREATED, "assignment": assignment,
            }

    def get_pg(self, pg_id: PlacementGroupID) -> Optional[dict]:
        with self._lock:
            return self.placement_groups.get(pg_id)

    def remove_pg(self, pg_id: PlacementGroupID) -> Optional[dict]:
        with self._lock:
            rec = self.placement_groups.pop(pg_id, None)
            if rec:
                rec["state"] = PG_REMOVED
            return rec

    # --------------------------------------------------------- snapshots
    # Explicit copies for state queries: both the in-process plane and the
    # remote client expose these, so node.py never touches raw attributes.
    def actors_snapshot(self) -> List[Tuple[ActorID, ActorRecord]]:
        with self._lock:
            return list(self.actors.items())

    def directory_snapshot(self) -> List[Tuple[ObjectID,
                                               Tuple[NodeID, ObjectMeta]]]:
        with self._lock:
            return list(self.directory.items())

    def pgs_snapshot(self) -> List[Tuple[PlacementGroupID, dict]]:
        with self._lock:
            return list(self.placement_groups.items())

    # ------------------------------------------------------------- events
    def record_task_event(self, ev: TaskEvent) -> None:
        with self._lock:
            self.task_events.append(ev)

    def list_task_events(self, limit: int = 1000) -> List[TaskEvent]:
        with self._lock:
            return list(self.task_events)[-limit:]

    # ------------------------------------------------------------- pubsub
    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        """In-process pubsub (reference analogue: ``src/ray/pubsub/`` long-poll
        channels). Callbacks run on the publisher's thread; keep them cheap."""
        with self._lock:
            self._subscribers.setdefault(channel, []).append(callback)

    def publish(self, channel: str, payload: Any) -> None:
        with self._lock:
            subs = list(self._subscribers.get(channel, ()))
        for cb in subs:
            try:
                cb(payload)
            except Exception:
                pass
