"""Control plane: cluster membership, actor/job/PG registries, KV, directory.

Equivalent role to the reference's GCS server (``src/ray/gcs/gcs_server/`` —
GcsNodeManager, GcsActorManager, GcsPlacementGroupManager, GcsKVManager,
GcsTaskManager) plus the ownership-based object directory
(``object_manager/ownership_based_object_directory.h``). In this build the
control plane is an in-process, thread-safe object: on a single host it is
embedded in the node service; an in-process multi-node cluster
(``ray_tpu.cluster_utils.Cluster``) shares one instance between node
services, mirroring the reference's single-GCS topology. Cross-host
deployment puts this behind the same framed-socket RPC used everywhere else.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import fieldsan
from . import history as history_mod
from . import locksan
from . import telemetry
from .config import CONFIG
from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID
from .object_store import ObjectMeta
from .protocol import ActorSpec, PlacementGroupSpec

M_EVENTS_EVICTED = telemetry.define(
    "counter", "rtpu_events_evicted_total",
    "Cluster events silently dropped from the bounded control-plane "
    "ring (oldest-first, at cluster_events_buffer_size) — silent "
    "history loss made observable")

# Actor lifecycle states (reference: gcs.proto ActorTableData.ActorState)
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"

PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"
# restored from a previous head's journal: the assigned nodes are dead,
# so the record is history only, never a placement target
PG_LOST = "LOST"


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str                      # unix socket path OR "host:port" (TCP)
    resources_total: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    # in-process shortcut to the NodeService (same-process multi-node cluster)
    service: Any = None
    # OS-host identity: node processes on one host share /dev/shm, so
    # same-host peers exchange objects zero-copy by shm name while
    # cross-host peers pull payload bytes (reference: local plasma vs
    # ``object_manager.h:117`` chunked Push/Pull)
    host: str = ""
    # availability reported with heartbeats (RaySyncer-equivalent resource
    # gossip for nodes the scheduler can't snapshot in-process)
    resources_available: Dict[str, float] = field(default_factory=dict)
    # queued resource demand reported with heartbeats (autoscaler input;
    # reference: ResourceDemandScheduler's load report)
    pending_shapes: List[Dict[str, float]] = field(default_factory=list)
    # monotonic version of the availability view (RaySyncer-equivalent,
    # reference: ray_syncer.h:86 versioned snapshots) -- a delayed or
    # re-ordered heartbeat can never roll the view back
    resource_version: int = 0

    def __getstate__(self):
        # the live service object never crosses the wire
        state = dict(self.__dict__)
        state["service"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)


@dataclass
class ActorRecord:
    spec: ActorSpec
    state: str = ACTOR_PENDING
    node_id: Optional[NodeID] = None
    num_restarts: int = 0
    death_reason: str = ""


@dataclass
class JobRecord:
    job_id: JobID
    driver_pid: int
    start_time: float
    end_time: Optional[float] = None


@dataclass
class TaskEvent:
    """One task state transition, kept in a bounded ring for the state API
    (reference: ``GcsTaskManager``, ``gcs_task_manager.h:61``)."""

    task_id: TaskID
    name: str
    state: str
    node_id: Optional[NodeID]
    timestamp: float
    is_actor_task: bool = False
    # diagnosis inputs for the stall detector: the task's resource
    # demand, its target actor, and (for a dep-waiting task) the
    # object ids it still needs
    resources: Optional[Dict[str, float]] = None
    actor_id: Optional[ActorID] = None
    pending_args: Optional[List[ObjectID]] = None


def aggregate_stacks(per_node: Dict[str, List[dict]]) -> List[dict]:
    """Dedup a cluster stack collection: threads with byte-identical
    stacks collapse into one group (at 100+ workers most are parked in
    the same few loops — the interesting stack is the one that differs).
    Sorted most-common first."""
    groups: Dict[tuple, dict] = {}
    for node_hex, dumps in (per_node or {}).items():
        for dump in dumps or []:
            for th in dump.get("threads", ()):
                key = tuple(th.get("frames", ()))
                g = groups.get(key)
                if g is None:
                    g = groups[key] = {"frames": list(key), "count": 0,
                                       "threads": []}
                g["count"] += 1
                g["threads"].append({
                    "node": node_hex,
                    "kind": dump.get("kind"),
                    "pid": dump.get("pid"),
                    "worker_id": dump.get("worker_id"),
                    "thread": th.get("thread_name"),
                })
    return sorted(groups.values(), key=lambda g: -g["count"])


class _CompactingStorage:
    """Wraps a GCS storage backend with size-triggered compaction: a
    long-lived head otherwise grows its journal without bound under
    KV/job churn (every overwrite appends). Compaction runs inline on
    the appending thread, already under the plane lock."""

    _COMPACT_EVERY = 20_000

    def __init__(self, inner, plane):
        self._inner = inner
        self._plane = plane
        self._appends = 0

    def append(self, entry) -> None:
        self._inner.append(entry)
        self._appends += 1
        if self._appends >= self._COMPACT_EVERY:
            self._appends = 0
            self._inner.compact(self._plane._durable_snapshot())

    def load(self):
        return self._inner.load()

    def compact(self, snapshot) -> None:
        self._appends = 0
        self._inner.compact(snapshot)

    def close(self) -> None:
        self._inner.close()


@fieldsan.guarded
class GlobalControlPlane:
    """Thread-safe cluster-wide registries.

    ``storage`` (see ``gcs_storage.py``) makes the durable tables — KV,
    jobs, placement-group specs — survive a head restart, the role
    Redis plays for the reference's GCS
    (``src/ray/gcs/store_client/redis_store_client.h:33``). Volatile
    state (directory, refcounts, heartbeats) dies with the process that
    owned it and is rebuilt by re-registration.
    """

    def __init__(self, storage=None):
        from . import gcs_storage
        self._storage = _CompactingStorage(
            storage or gcs_storage.InMemoryStorage(), self)
        self._lock = locksan.rlock("gcs.plane")
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.jobs: Dict[JobID, JobRecord] = {}
        self.kv: Dict[bytes, bytes] = {}
        self.placement_groups: Dict[PlacementGroupID, dict] = {}
        # object directory: object -> (node_id, meta)
        self.directory: Dict[ObjectID, Tuple[NodeID, ObjectMeta]] = {}
        # streaming-return counters per producing task (see gen_update)
        self.gen_streams: Dict[TaskID, dict] = {}
        # unplaceable placement groups awaiting capacity (autoscaler
        # input; see register_pending_pg)
        self.pending_pgs: Dict[PlacementGroupID, dict] = {}
        self.task_events: deque = deque(maxlen=CONFIG.task_events_buffer_size)
        self.cluster_events: deque = deque(
            maxlen=CONFIG.cluster_events_buffer_size)
        # node/actor/PG lifecycle state transitions, retained past death
        # in their own bounded ring (task transitions already live in
        # task_events) so `state.timeline()`, the dashboard and debug
        # bundles can render "what the cluster was doing" after the
        # subject is gone
        self.lifecycle_events: deque = deque(
            maxlen=CONFIG.cluster_events_buffer_size)
        self._events_evicted = 0
        # metrics history: multi-resolution retention rings fed by the
        # hosting node's tick (record_history_snapshot); interval digest
        # deltas accumulate here between ticks so each frame carries a
        # true windowed quantile sketch, not a cumulative one
        self.metrics_history = history_mod.MetricsHistory(
            CONFIG.metrics_history_capacity,
            CONFIG.metrics_history_steps,
            CONFIG.metrics_history_max_bytes)
        self._history_interval_digests: Dict[tuple, dict] = {}
        self._history_last = 0.0
        self.spans: deque = deque(maxlen=CONFIG.span_buffer_size)
        # cluster-wide metrics table: merged deltas from every process's
        # telemetry shards (reference analogue: the head's Prometheus
        # scrape target aggregating per-node MetricsAgents)
        self.metrics_counters: Dict[tuple, float] = {}
        self.metrics_gauges: Dict[tuple, tuple] = {}      # key -> (val, ts)
        # retired gauge series -> delete-marker ts: a straggling publish
        # from the dying process (its flusher racing the delete) must
        # not resurrect a popped series, so older-ts values are refused
        # until a genuinely newer set re-creates it
        self._gauge_tombstones: Dict[tuple, float] = {}
        self.metrics_hists: Dict[tuple, dict] = {}
        # key -> digest payload (centroids/count/sum/min/max); merged by
        # the t-digest fold, so per-process quantile sketches combine
        self.metrics_digests: Dict[tuple, dict] = {}
        self.metrics_meta: Dict[str, dict] = {}
        # distinct series refused (cardinality cap) / bucket-conflicted:
        # sets, not event counters — every flush retries the same key
        # and must not inflate the count
        self._metrics_dropped_keys: set = set()
        self._metrics_conflict_keys: set = set()
        self._subscribers: Dict[str, List[Callable[[Any], None]]] = {}
        # distributed reference counting (reference: reference_count.h:61):
        # holder = (node_id_bin, conn_key) — one entry per process holding
        # at least one local ref; pins = in-flight submitted tasks using
        # the object as an argument
        self.ref_holders: Dict[ObjectID, set] = {}
        self.ref_pins: Dict[ObjectID, int] = {}
        self._task_arg_refs: Dict[TaskID, List[ObjectID]] = {}
        self._task_pin_owner: Dict[TaskID, NodeID] = {}
        # returns whose refs all died BEFORE the task sealed them: the
        # seal must free them immediately (fire-and-forget tasks)
        self._freed_early: set = set()
        # refs pickled INSIDE a return object (worker RETURN_REFS):
        # pinned until the return itself is freed, so a nested ref's
        # object survives the gap between the producer's locals dying
        # and a consumer deserializing the return
        self._contained_pins: Dict[ObjectID, List[ObjectID]] = {}
        # RETURN_REFS that arrived before the submitter's REF_REGISTER
        # of the holder (a fast task's worker conn can outrun the
        # driver's buffered edge flush): parked — NOT pinned — until
        # the holder registers, then promoted to a real contained pin.
        # holder_oid -> (oids, parked_at); TTL-swept so a
        # fire-and-forget holder whose register never comes can't
        # accumulate records
        self._contained_pending: Dict[ObjectID, tuple] = {}
        # zero-count objects in their free-grace window (oid -> deadline;
        # see _schedule_zero_locked)
        self._zero_pending: Dict[ObjectID, float] = {}
        # lineage: creating TaskSpec per return object, for reconstruction
        # (reference: object_recovery_manager.h:90), bounded by
        # CONFIG.max_lineage_bytes
        self.lineage: Dict[ObjectID, Any] = {}
        self._lineage_live: Dict[TaskID, int] = {}   # live return oids/spec
        self._lineage_bytes = 0
        # reconstruction claims: only one node rebuilds a lost object, and
        # only objects that were sealed at least once are "lost" (an
        # in-flight first execution must never be duplicated)
        self._sealed_once: set = set()
        self._reconstruct_claims: Dict[ObjectID, float] = {}
        # successful claims per object, for the chaos tests' exactly-once
        # assertion (a depth-N chain rebuilds each link once)
        self._reconstruct_counts: Dict[ObjectID, int] = {}
        # checkpointable actors: actor -> (seq, blob, ts). Latest only —
        # the control plane holds the blob (NOT the checkpointing
        # node's object store) so a node-death restart on another node
        # still restores; GC'd when the actor reaches ACTOR_DEAD
        self.actor_checkpoints: Dict[ActorID, tuple] = {}
        # specs of restartable actors whose node died, awaiting a
        # claimant (see claim_actor_reroute)
        self._actor_reroutes: Dict[ActorID, Any] = {}
        # stall detector state: last sweep time + cause already warned
        # per task (re-warn only when the diagnosed cause changes)
        self._stall_last_sweep = 0.0
        self._stall_warned: Dict[TaskID, str] = {}
        # object provenance: oid -> (callsite, creator) captured at
        # put()/.remote() time (reference: ReferenceCounter callsites
        # behind RAY_record_ref_creation_sites); dies with the object
        self.obj_provenance: Dict[ObjectID, tuple] = {}
        # leak-sweep state: current findings, first-seen time of
        # zero-holder-but-pinned objects, and the cause already warned
        # per object (emit-once until the cause changes)
        self._leaks: Dict[ObjectID, dict] = {}
        self._pinned_zero_since: Dict[ObjectID, float] = {}
        self._leak_warned: Dict[ObjectID, str] = {}
        self._leak_last_sweep = 0.0
        self._restore()

    # ------------------------------------------------------- persistence
    # concurrency: requires(gcs.plane)
    def _restore(self) -> None:
        """Replay the journal into the durable tables (no-op in-memory)."""
        for table, op, payload in self._storage.load():
            if table == "kv":
                if op == "put" and self._kv_durable(payload[0]):
                    self.kv[payload[0]] = payload[1]
                elif op == "del":
                    self.kv.pop(payload, None)
            elif table == "jobs" and op == "put":
                # a job still "running" in the journal died with the old
                # head (its driver is gone); stamp it finished so it
                # doesn't show as live forever
                if payload.end_time is None:
                    payload.end_time = time.time()
                self.jobs[payload.job_id] = payload
            elif table == "pgs":
                if op == "put":
                    # the nodes behind the old assignment died with the
                    # old head: keep the record for history/inspection
                    # but never as a live placement target
                    rec = dict(payload)
                    rec["state"] = "LOST"
                    self.placement_groups[payload["spec"].pg_id] = rec
                elif op == "del":
                    self.placement_groups.pop(payload, None)

    def _durable_snapshot(self) -> list:
        with self._lock:
            return ([("kv", "put", (k, v)) for k, v in self.kv.items()
                     if self._kv_durable(k)]
                    + [("jobs", "put", r) for r in self.jobs.values()]
                    + [("pgs", "put", r)
                       for r in self.placement_groups.values()])

    def compact_storage(self) -> None:
        # under the plane lock: an append between snapshot and the
        # journal rename would be destroyed by the rename (a kv_put
        # that returned True silently losing durability)
        with self._lock:
            self._storage.compact(self._durable_snapshot())

    def close_storage(self) -> None:
        self._storage.close()

    # ------------------------------------------------------------- nodes
    def register_node(self, info: NodeInfo) -> None:
        # re-stamp on OUR clock: a remote registrant's monotonic stamp is
        # incomparable with this host's and could instantly trip the
        # heartbeat sweeper
        info.last_heartbeat = time.monotonic()
        with self._lock:
            self.nodes[info.node_id] = info
            self._record_lifecycle_locked("node", info.node_id.hex(),
                                          "ALIVE", address=info.address)
        self.publish("NODE", {"node_id": info.node_id, "state": "ALIVE"})

    def remove_node(self, node_id: NodeID, reason: str = "") -> None:
        dead_actors: List[ActorID] = []
        restart_actors: List[ActorID] = []
        with self._lock:
            info = self.nodes.get(node_id)
            if info is None:
                return
            info.alive = False
            self._record_lifecycle_locked("node", node_id.hex(), "DEAD",
                                          reason=reason)
            # drop directory entries whose only location was this node
            lost = [oid for oid, (nid, _) in self.directory.items()
                    if nid == node_id]
            for oid in lost:
                del self.directory[oid]
            for aid, rec in self.actors.items():
                if rec.node_id != node_id or rec.state == ACTOR_DEAD:
                    continue
                max_r = rec.spec.max_restarts
                if max_r == -1 or rec.num_restarts < max_r:
                    # restartable actor lost its whole node: hand the
                    # spec to exactly one surviving claimant (reference:
                    # GcsActorManager::OnNodeDead rescheduling)
                    rec.num_restarts += 1
                    rec.state = ACTOR_RESTARTING
                    rec.node_id = None
                    self._actor_reroutes[aid] = rec.spec
                    restart_actors.append(aid)
                else:
                    dead_actors.append(aid)
            # release arg pins whose submitting node can never unpin
            orphans = [tid for tid, owner in self._task_pin_owner.items()
                       if owner == node_id]
            for tid in orphans:
                self._unpin_locked(tid)
        self.publish("NODE", {"node_id": node_id, "state": "DEAD",
                              "reason": reason})
        # drain the released pins even if no further ref edges arrive
        # (e.g. the cluster just collapsed to its last node)
        self.sweep_ref_zeros()
        for aid in restart_actors:
            self.publish("ACTOR", {"actor_id": aid,
                                   "state": ACTOR_RESTARTING,
                                   "reroute": True})
        for aid in dead_actors:
            self.set_actor_state(aid, ACTOR_DEAD,
                                 reason=f"node {node_id} died")

    def claim_actor_reroute(self, actor_id: ActorID):
        """Exactly-once handoff of a node-death restart: nodes race on
        the ACTOR/reroute event; the first claim wins the spec."""
        with self._lock:
            return self._actor_reroutes.pop(actor_id, None)

    def requeue_actor_reroute(self, actor_id: ActorID, spec) -> None:
        """A claimant failed mid-restart: put the spec back and re-ask."""
        with self._lock:
            self._actor_reroutes[actor_id] = spec
        self.publish("ACTOR", {"actor_id": actor_id,
                               "state": ACTOR_RESTARTING, "reroute": True})

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    def heartbeat(self, node_id: NodeID,
                  resources_available: Optional[Dict[str, float]] = None,
                  pending_shapes: Optional[List[Dict[str, float]]] = None,
                  version: Optional[int] = None) -> None:
        """Liveness + versioned resource sync. A payload carrying a
        version at or below the stored one is a delayed duplicate: it
        refreshes liveness but must NOT roll the availability view back
        (reference: RaySyncer versioned snapshots, ray_syncer.h:86).
        ``resources_available=None`` is the delta protocol "nothing
        changed" ping -- senders only ship the dict on change."""
        with self._lock:
            info = self.nodes.get(node_id)
            if info:
                info.last_heartbeat = time.monotonic()
                stale = (version is not None
                         and info.resource_version > 0
                         and version <= info.resource_version)
                if not stale:
                    if version is not None:
                        info.resource_version = version
                    if resources_available is not None:
                        info.resources_available = resources_available
                    if pending_shapes is not None:
                        info.pending_shapes = pending_shapes
        # heartbeats double as the grace sweeper so pending frees drain
        # even when no further ref edges arrive
        self.sweep_ref_zeros()

    def get_node(self, node_id: NodeID) -> Optional[NodeInfo]:
        with self._lock:
            return self.nodes.get(node_id)

    def nodes_snapshot(self) -> List[NodeInfo]:
        with self._lock:
            return list(self.nodes.values())

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.alive_nodes():
            for k, v in n.resources_total.items():
                out[k] = out.get(k, 0.0) + v
        return out

    # ------------------------------------------------------------ actors
    def register_actor(self, spec: ActorSpec) -> ActorRecord:
        rec = ActorRecord(spec=spec)
        with self._lock:
            if spec.registered_name:
                key = (spec.namespace, spec.registered_name)
                if key in self.named_actors:
                    raise ValueError(
                        f"actor name {spec.registered_name!r} already taken "
                        f"in namespace {spec.namespace!r}")
                self.named_actors[key] = spec.actor_id
            self.actors[spec.actor_id] = rec
        return rec

    def set_actor_state(self, actor_id: ActorID, state: str,
                        node_id: Optional[NodeID] = None,
                        reason: str = "",
                        count_restart: bool = False) -> None:
        with self._lock:
            rec = self.actors.get(actor_id)
            if rec is None:
                return
            if rec.state != state:
                self._record_lifecycle_locked(
                    "actor", actor_id.hex(), state,
                    class_name=rec.spec.name, reason=reason or None)
            rec.state = state
            if count_restart:
                # worker-level restarts and node-death reroutes share ONE
                # budget: max_restarts bounds their SUM
                rec.num_restarts += 1
            if node_id is not None:
                rec.node_id = node_id
            if reason:
                rec.death_reason = reason
            if state == ACTOR_DEAD and rec.spec.registered_name:
                self.named_actors.pop(
                    (rec.spec.namespace, rec.spec.registered_name), None)
            if state == ACTOR_DEAD:
                # terminal: nothing will ever restore this checkpoint
                self.actor_checkpoints.pop(actor_id, None)
        self.publish("ACTOR", {"actor_id": actor_id, "state": state,
                               "reason": reason})

    def get_actor(self, actor_id: ActorID) -> Optional[ActorRecord]:
        with self._lock:
            return self.actors.get(actor_id)

    def lookup_named_actor(self, name: str,
                           namespace: str = "default") -> Optional[ActorRecord]:
        with self._lock:
            actor_id = self.named_actors.get((namespace, name))
            return self.actors.get(actor_id) if actor_id else None

    # ------------------------------------------------ actor checkpoints
    # Opt-in checkpointable-actor state (save_checkpoint/
    # restore_checkpoint): one latest blob per actor, seq-guarded so a
    # pre-death straggler's late save can never roll a restarted
    # actor's newer snapshot back.

    def save_actor_checkpoint(self, actor_id: ActorID, seq: int,
                              blob: bytes) -> bool:
        with self._lock:
            cur = self.actor_checkpoints.get(actor_id)
            if cur is not None and cur[0] >= seq:
                return False
            self.actor_checkpoints[actor_id] = (int(seq), bytes(blob),
                                                time.time())
        return True

    def get_actor_checkpoint(self, actor_id: ActorID
                             ) -> Optional[Tuple[int, bytes]]:
        with self._lock:
            cur = self.actor_checkpoints.get(actor_id)
            return None if cur is None else (cur[0], cur[1])

    # Durable mutations journal INSIDE the plane lock: an append racing
    # a later append for the same key would otherwise persist in the
    # wrong order, and a restart would restore a value the live cluster
    # never ended on. FileStorage.append is a short local write with its
    # own lock and never calls back into the plane, so no deadlock.

    # -------------------------------------------------------------- jobs
    def register_job(self, rec: JobRecord) -> None:
        with self._lock:
            self.jobs[rec.job_id] = rec
            self._storage.append(("jobs", "put", rec))

    def finish_job(self, job_id: JobID) -> None:
        with self._lock:
            rec = self.jobs.get(job_id)
            if rec:
                rec.end_time = time.time()
                self._storage.append(("jobs", "put", rec))

    # ---------------------------------------------------------------- kv
    # never journaled: per-session function blobs (``fn:``, megabytes of
    # pickled code dead with their job) and runtime discovery keys
    # (``__rtpu_*`` — a restarted head re-publishes fresh addresses, and
    # restoring stale ones would point drivers at dead sockets)
    _VOLATILE_KV_PREFIXES = (b"fn:", b"__rtpu_")

    def _kv_durable(self, key: bytes) -> bool:
        return not key.startswith(self._VOLATILE_KV_PREFIXES)

    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        with self._lock:
            if not overwrite and key in self.kv:
                return False
            self.kv[key] = value
            if self._kv_durable(key):
                self._storage.append(("kv", "put", (key, value)))
        return True

    def kv_get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(key)

    def kv_del(self, key: bytes) -> None:
        with self._lock:
            self.kv.pop(key, None)
            if self._kv_durable(key):
                self._storage.append(("kv", "del", key))

    def kv_keys(self, prefix: bytes) -> List[bytes]:
        with self._lock:
            return [k for k in self.kv if k.startswith(prefix)]

    # ---------------------------------------------------------- directory
    def publish_location(self, object_id: ObjectID, node_id: NodeID,
                         meta: ObjectMeta) -> None:
        with self._lock:
            self.directory[object_id] = (node_id, meta)
            self._sealed_once.add(object_id)
            self._reconstruct_claims.pop(object_id, None)
            garbage = object_id in self._freed_early
            if garbage:
                self._freed_early.discard(object_id)
        if garbage:
            # every ref died before the value was sealed (fire-and-forget
            # task): the fresh copy is garbage on arrival
            self.publish("REF_ZERO", {"object_id": object_id,
                                      "node_id": node_id})

    def lookup_location(
            self, object_id: ObjectID) -> Optional[Tuple[NodeID, ObjectMeta]]:
        with self._lock:
            return self.directory.get(object_id)

    def drop_location(self, object_id: ObjectID) -> None:
        with self._lock:
            self.directory.pop(object_id, None)
            # explicit free (ray_tpu free()) of a return releases its
            # nested-ref pins; the refcount zero path already popped
            # them in _zero_check, so this is a no-op there
            self._release_contained_locked(object_id)
        self.sweep_ref_zeros()

    # ------------------------------------------------- pending gangs
    # Placement groups that could not be packed onto the live cluster.
    # The client retries create_pg while blocked in ready(); these
    # records make that demand visible to the autoscaler, which is THE
    # scaling driver for gang workloads on TPU (reference:
    # ``resource_demand_scheduler.py:102`` feeds pending placement
    # groups into scale-up). last_attempt is refreshed per retry so a
    # vanished driver's gang stops driving scale-up (staleness filter).

    # purge records this long after their last retry: abandoned gangs
    # (ready() timeout, dead driver) must not leak for the cluster's
    # lifetime. Well past the autoscaler's 5s staleness bar.
    PENDING_PG_TTL_S = 60.0

    def register_pending_pg(self, spec) -> None:
        with self._lock:
            self._purge_stale_pending_pgs()
            self.pending_pgs[spec.pg_id] = {"spec": spec,
                                            "last_attempt": time.time()}

    def clear_pending_pg(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            self.pending_pgs.pop(pg_id, None)

    def pending_pgs_snapshot(self) -> List[dict]:
        with self._lock:
            self._purge_stale_pending_pgs()
            return [dict(rec) for rec in self.pending_pgs.values()]

    # concurrency: requires(gcs.plane)
    def _purge_stale_pending_pgs(self) -> None:
        cutoff = time.time() - self.PENDING_PG_TTL_S
        for pg_id in [p for p, rec in self.pending_pgs.items()
                      if rec["last_attempt"] < cutoff]:
            del self.pending_pgs[pg_id]

    # ------------------------------------------------- generator streams
    # Streaming-return bookkeeping (reference: the owner-side generator
    # state driven by ReportGeneratorItemReturns,
    # ``core_worker.proto:396``). Item payloads are ordinary directory
    # objects; this records only produced/consumed/done counters so a
    # consumer on any node can pace the producer.

    def gen_update(self, task_id: TaskID, produced: int) -> None:
        with self._lock:
            st = self.gen_streams.setdefault(
                task_id, {"produced": 0, "consumed": 0, "done": False,
                          "count": None, "error": None})
            if produced > st["produced"]:
                st["produced"] = produced
        self.publish("GEN", (task_id, "produced", produced))

    def gen_done(self, task_id: TaskID, count: int,
                 error: Optional[bytes]) -> None:
        with self._lock:
            st = self.gen_streams.setdefault(
                task_id, {"produced": 0, "consumed": 0, "done": False,
                          "count": None, "error": None})
            st["done"] = True
            st["count"] = count
            st["error"] = error
            st["produced"] = max(st["produced"], count)
        self.publish("GEN", (task_id, "done", count))

    def gen_consumed(self, task_id: TaskID, consumed: int) -> None:
        with self._lock:
            # create-on-miss: a GEN_CLOSE can arrive before the first
            # produced item, and dropping its infinite credit would
            # wedge the producer at the backpressure window forever
            st = self.gen_streams.setdefault(
                task_id, {"produced": 0, "consumed": 0, "done": False,
                          "count": None, "error": None})
            if consumed <= st["consumed"]:
                return
            st["consumed"] = consumed
        self.publish("GEN", (task_id, "consumed", consumed))

    def gen_get(self, task_id: TaskID) -> Optional[dict]:
        with self._lock:
            st = self.gen_streams.get(task_id)
            return dict(st) if st is not None else None

    def gen_drop(self, task_id: TaskID) -> None:
        with self._lock:
            self.gen_streams.pop(task_id, None)

    # ----------------------------------------------------- placement groups
    def register_pg(self, spec: PlacementGroupSpec,
                    assignment: List[NodeID]) -> None:
        rec = {"spec": spec, "state": PG_CREATED, "assignment": assignment}
        with self._lock:
            self.placement_groups[spec.pg_id] = rec
            self._record_lifecycle_locked("placement_group",
                                          spec.pg_id.hex(), PG_CREATED)
            self._storage.append(("pgs", "put", rec))

    def get_pg(self, pg_id: PlacementGroupID) -> Optional[dict]:
        with self._lock:
            return self.placement_groups.get(pg_id)

    def remove_pg(self, pg_id: PlacementGroupID) -> Optional[dict]:
        with self._lock:
            rec = self.placement_groups.pop(pg_id, None)
            if rec:
                rec["state"] = PG_REMOVED
                self._record_lifecycle_locked("placement_group",
                                              pg_id.hex(), PG_REMOVED)
                self._storage.append(("pgs", "del", pg_id))
        return rec

    # ------------------------------------------------- reference counting
    def ref_register(self, oid: ObjectID, holder: tuple) -> None:
        with self._lock:
            self.ref_holders.setdefault(oid, set()).add(holder)
            # a borrow landed during the zero-grace window: cancel the
            # pending free (see _schedule_zero_locked)
            self._zero_pending.pop(oid, None)
            pend = self._contained_pending.pop(oid, None)
            if pend is not None:
                # a RETURN_REFS raced ahead of this register (see
                # pin_contained): promote the parked containment now
                # that the holder is live
                self._pin_contained_locked(oid, pend[0])

    def ref_drop(self, oid: ObjectID, holder: tuple) -> None:
        with self._lock:
            holders = self.ref_holders.get(oid)
            if holders is None:
                return   # never tracked (or already freed): not ours
            holders.discard(holder)
            self._schedule_zero_locked(oid)
        self.sweep_ref_zeros()

    def drop_all_refs(self, holder: tuple, oids: List[ObjectID]) -> None:
        """A holder process died/disconnected: drop everything it held."""
        with self._lock:
            for oid in oids:
                holders = self.ref_holders.get(oid)
                if holders is None:
                    continue
                holders.discard(holder)
                self._schedule_zero_locked(oid)
        self.sweep_ref_zeros()

    # concurrency: requires(gcs.plane)
    def _schedule_zero_locked(self, oid: ObjectID) -> None:
        """Count hit zero: schedule the free after a short grace window
        instead of freeing now. A ref travelling between processes (a
        queue actor returns [ref] and drops its copy while the consumer's
        REGISTER is still in flight) briefly reads as zero; freeing
        immediately would vaporize the object under the borrower.
        Reference analogue: the owner-hosted borrower protocol
        (WaitForRefRemoved, ``reference_count.h:61``) — the centralized
        design absorbs edge races with time instead of per-borrower
        chains."""
        holders = self.ref_holders.get(oid)
        if holders is None or holders or self.ref_pins.get(oid, 0) > 0:
            return
        self._zero_pending.setdefault(
            oid, time.time() + CONFIG.ref_zero_grace_ms / 1000.0)

    def sweep_ref_zeros(self) -> None:
        """Publish frees whose grace expired with the count still zero.
        Called from the edge paths and from heartbeats (so zeros drain
        even on an otherwise-idle cluster)."""
        if not self._zero_pending:
            return          # lock-free fast path: called per edge event
        freed = []
        now = time.time()
        with self._lock:
            if not self._zero_pending:
                return
            for oid, deadline in list(self._zero_pending.items()):
                if deadline > now:
                    continue
                del self._zero_pending[oid]
                z = self._zero_check(oid)
                if z is not None:
                    freed.append(z)
        for z in freed:
            self.publish("REF_ZERO", z)

    def pin_task_args(self, task_id: TaskID, oids: List[ObjectID],
                      owner_node: Optional[NodeID] = None) -> None:
        """Submitted-task references: args keep their objects alive for
        the task's lifetime even if every Python ref dies meanwhile.
        ``owner_node`` (the submitting node) lets ``remove_node`` release
        pins whose owner can never send the unpin."""
        with self._lock:
            self._task_arg_refs[task_id] = list(oids)
            if owner_node is not None:
                self._task_pin_owner[task_id] = owner_node
            for oid in oids:
                self.ref_pins[oid] = self.ref_pins.get(oid, 0) + 1

    def unpin_task_args(self, task_id: TaskID) -> None:
        with self._lock:
            self._unpin_locked(task_id)
        self.sweep_ref_zeros()

    # concurrency: requires(gcs.plane)
    def _unpin_locked(self, task_id: TaskID) -> None:
        self._task_pin_owner.pop(task_id, None)
        for oid in self._task_arg_refs.pop(task_id, ()):
            n = self.ref_pins.get(oid, 1) - 1
            if n <= 0:
                self.ref_pins.pop(oid, None)
            else:
                self.ref_pins[oid] = n
            self._schedule_zero_locked(oid)

    def pin_contained(self, holder_oid: ObjectID,
                      oids: List[ObjectID]) -> None:
        """A task return carries these refs inside its payload: keep
        their objects alive until the return object is freed. A repeat
        for the same return (task retry) replaces the previous pin set."""
        with self._lock:
            if self.ref_holders.get(holder_oid) is None:
                # Two indistinguishable cases: the return's refs already
                # died (fire-and-forget — nested objects are garbage,
                # don't pin) OR a fast task's RETURN_REFS outran the
                # submitter's buffered REF_REGISTER edge. Park WITHOUT
                # pinning: a late register promotes it (see
                # ref_register); a register that never comes is
                # TTL-swept, so garbage stays garbage either way.
                self._contained_pending[holder_oid] = (list(oids),
                                                       time.time())
                return
            self._pin_contained_locked(holder_oid, oids)

    # concurrency: requires(gcs.plane)
    def _pin_contained_locked(self, holder_oid: ObjectID,
                              oids: List[ObjectID]) -> None:
        self._release_contained_locked(holder_oid)
        self._contained_pins[holder_oid] = list(oids)
        for oid in oids:
            self.ref_pins[oid] = self.ref_pins.get(oid, 0) + 1
            self._zero_pending.pop(oid, None)

    # concurrency: requires(gcs.plane)
    def _release_contained_locked(self, holder_oid: ObjectID) -> None:
        self._contained_pending.pop(holder_oid, None)
        for oid in self._contained_pins.pop(holder_oid, ()):
            n = self.ref_pins.get(oid, 1) - 1
            if n <= 0:
                self.ref_pins.pop(oid, None)
                self._schedule_zero_locked(oid)
            else:
                self.ref_pins[oid] = n

    # concurrency: requires(gcs.plane)
    def _zero_check(self, oid: ObjectID):
        """Callers hold _lock. Returns a REF_ZERO payload when the object
        became garbage: it was tracked, no process holds a ref, and no
        in-flight task uses it."""
        holders = self.ref_holders.get(oid)
        if holders is None or holders or self.ref_pins.get(oid, 0) > 0:
            return None
        del self.ref_holders[oid]
        # provenance, leak-sweep and reconstruction-audit state die
        # with the object
        self._reconstruct_counts.pop(oid, None)
        self.obj_provenance.pop(oid, None)
        self._leaks.pop(oid, None)
        self._pinned_zero_since.pop(oid, None)
        self._leak_warned.pop(oid, None)
        # nested refs this return carried die with it (cascading via
        # their own zero-grace)
        self._release_contained_locked(oid)
        spec = self.lineage.pop(oid, None)
        if spec is not None:
            # spec cost was charged once for all returns: release it when
            # the last live return goes
            live = self._lineage_live.get(spec.task_id, 1) - 1
            if live <= 0:
                self._lineage_live.pop(spec.task_id, None)
                self._lineage_bytes -= self._spec_cost(spec)
            else:
                self._lineage_live[spec.task_id] = live
        loc = self.directory.get(oid)
        if loc is None:
            # refs died before the task sealed its return: mark so the
            # eventual seal frees the value instead of leaking it
            self._freed_early.add(oid)
        return {"object_id": oid,
                "node_id": loc[0] if loc is not None else None}

    # --------------------------- object provenance & memory introspection
    # Reference surface: ``ray memory`` — the ReferenceCounter's
    # per-ref creation callsites (RAY_record_ref_creation_sites) plus
    # ref-type classification (LOCAL_REFERENCE / USED_BY_PENDING_TASK /
    # CAPTURED_IN_OBJECT / ACTOR_HANDLE / PINNED_IN_STORE). Everything
    # here derives from state the plane already keeps (ref_holders,
    # ref_pins, _task_arg_refs, _contained_pins, actor specs); the only
    # new ingestion is the OBJ_PROVENANCE callsite batches.

    _PROVENANCE_LIMIT = 200_000

    def record_provenance(self, entries: List[tuple]) -> None:
        """Merge one client's creation-callsite batch: (oid, callsite,
        creator) triples. Capped so runaway id churn can't grow the
        head without bound; the leak sweep GCs entries whose object is
        gone."""
        with self._lock:
            table = self.obj_provenance
            for oid, callsite, creator in entries:
                if oid in table or len(table) < self._PROVENANCE_LIMIT:
                    table[oid] = (callsite, creator)

    def objects_info(self, oids: List[ObjectID]) -> Dict[ObjectID, dict]:
        """Size + location + provenance for a batch of ids in ONE call
        (the OOM autopsy names a victim's top objects without an RPC
        per id)."""
        out: Dict[ObjectID, dict] = {}
        with self._lock:
            for oid in oids:
                loc = self.directory.get(oid)
                prov = self.obj_provenance.get(oid)
                out[oid] = {
                    "object_id": oid,
                    "size": loc[1].size if loc is not None else None,
                    "node_id": loc[0] if loc is not None else None,
                    "callsite": prov[0] if prov else None,
                    "creator": prov[1] if prov else None,
                }
        return out

    def memory_state(self) -> dict:
        """One consistent snapshot of the object ledger: every object
        the plane knows (directory entries, held refs, pinned args,
        contained pins) with its size, creation callsite and a
        per-holder reference-type breakdown. The raw material behind
        ``state.list_objects()`` / ``state.memory_summary()`` /
        ``GET /api/memory``."""
        with self._lock:
            task_pins: Dict[ObjectID, int] = {}
            for oids in self._task_arg_refs.values():
                for oid in oids:
                    task_pins[oid] = task_pins.get(oid, 0) + 1
            contained: Dict[ObjectID, int] = {}
            for oids in self._contained_pins.values():
                for oid in oids:
                    contained[oid] = contained.get(oid, 0) + 1
            actor_returns: Dict[ObjectID, ActorID] = {}
            for aid, rec in self.actors.items():
                cr = rec.spec.creation_return_id
                if cr is not None and rec.state != ACTOR_DEAD:
                    actor_returns[cr] = aid
            universe = (set(self.directory) | set(self.ref_holders)
                        | set(task_pins) | set(contained))
            rows: List[dict] = []
            for oid in universe:
                loc = self.directory.get(oid)
                prov = self.obj_provenance.get(oid)
                holders = self.ref_holders.get(oid) or ()
                ref_types: Dict[str, int] = {}
                if holders:
                    ref_types["LOCAL_REFERENCE"] = len(holders)
                if task_pins.get(oid):
                    ref_types["USED_BY_PENDING_TASK"] = task_pins[oid]
                if contained.get(oid):
                    ref_types["CAPTURED_IN_OBJECT"] = contained[oid]
                if oid in actor_returns:
                    ref_types["ACTOR_HANDLE"] = 1
                rows.append({
                    "object_id": oid,
                    "node_id": loc[0] if loc is not None else None,
                    "size": loc[1].size if loc is not None else None,
                    "callsite": prov[0] if prov else None,
                    "creator": prov[1] if prov else None,
                    "ref_types": ref_types,
                    "pins": self.ref_pins.get(oid, 0),
                    "leaked": oid in self._leaks,
                })
            return {"objects": rows,
                    "leaks": [dict(r) for r in self._leaks.values()]}

    def sweep_object_leaks(self):
        """Rate-limited leak sweep: flag objects whose EVERY ref holder
        lives on a dead node (the node died before its processes could
        drop their refs — nothing will ever free them), and objects
        that sat pinned with zero holders past
        ``memory_leak_pinned_ttl_s`` (a task pin / contained pin whose
        release path is wedged). Returns ``(new_records, total)`` —
        ``new_records`` are findings not yet warned about (the caller
        emits them as OBJECT_LEAK WARNING events), ``total`` the
        current finding count for the gauge; ``([], None)`` when
        rate-limited or disabled."""
        interval = CONFIG.memory_leak_sweep_interval_s
        # interval<=0 disables leak FINDING only: the bookkeeping GC
        # below (parked containments, dead provenance entries) must
        # still run or a long-lived head grows without bound
        gc_only = interval <= 0
        period = interval if interval > 0 else 30.0
        now = time.time()
        out: List[dict] = []
        with self._lock:
            if now - self._leak_last_sweep < period:
                return [], None
            self._leak_last_sweep = now
            alive = {n.node_id.binary() for n in self.nodes.values()
                     if n.alive}
            ttl = CONFIG.memory_leak_pinned_ttl_s
            leaks: Dict[ObjectID, dict] = {}
            for oid, holders in (() if gc_only
                                 else self.ref_holders.items()):
                cause = None
                age = None
                if holders:
                    # holder = (node_id_binary, conn_key): a holder on
                    # a live node is (or will be) cleaned by that
                    # node's conn-close path; one on a dead node never
                    if all(h[0] not in alive for h in holders):
                        cause = "dead_holders"
                    self._pinned_zero_since.pop(oid, None)
                elif self.ref_pins.get(oid, 0) > 0:
                    since = self._pinned_zero_since.setdefault(oid, now)
                    age = now - since
                    if ttl > 0 and age >= ttl:
                        cause = "pinned_no_holder"
                if cause is None:
                    continue
                loc = self.directory.get(oid)
                prov = self.obj_provenance.get(oid)
                rec = {"object_id": oid, "cause": cause,
                       "node_id": loc[0] if loc is not None else None,
                       "size": loc[1].size if loc is not None else None,
                       "callsite": prov[0] if prov else None,
                       "creator": prov[1] if prov else None,
                       "holders": len(holders),
                       "pins": self.ref_pins.get(oid, 0)}
                if age is not None:
                    rec["age_s"] = round(age, 1)
                leaks[oid] = rec
                if self._leak_warned.get(oid) != cause:
                    self._leak_warned[oid] = cause
                    out.append(dict(rec))
            self._leaks = leaks
            # GC sweep state + provenance for objects that are fully
            # gone (freed, or never tracked at all)
            for d in (self._leak_warned, self._pinned_zero_since):
                for oid in [o for o in d if o not in self.ref_holders]:
                    del d[oid]
            for oid in [o for o in self.obj_provenance
                        if o not in self.ref_holders
                        and o not in self.directory
                        and not self.ref_pins.get(o)]:
                del self.obj_provenance[oid]
            # parked containments whose holder never registered
            # (fire-and-forget returns): drop after a generous TTL
            cutoff = now - 30.0
            for oid in [o for o, (_c, t) in
                        self._contained_pending.items() if t < cutoff]:
                del self._contained_pending[oid]
            return out, len(leaks)

    # --------------------------------------------------------------- lineage
    @staticmethod
    def _spec_cost(spec) -> int:
        cost = 256
        for slot, val in list(spec.args) + list(spec.kwargs.values()):
            if slot == "v":
                cost += len(val)
        return cost

    def record_lineage(self, spec) -> None:
        cost = self._spec_cost(spec)
        with self._lock:
            if spec.task_id in self._lineage_live:
                return   # resubmission of a recorded task
            if self._lineage_bytes + cost > CONFIG.max_lineage_bytes:
                return   # over budget: this object won't be reconstructable
            for oid in spec.return_ids:
                self.lineage[oid] = spec
            self._lineage_live[spec.task_id] = len(spec.return_ids)
            self._lineage_bytes += cost

    def get_lineage(self, oid: ObjectID):
        with self._lock:
            return self.lineage.get(oid)

    def claim_lineage(self, oid: ObjectID,
                      claim_timeout_s: float = 60.0):
        """Atomic reconstruction claim: returns the creating TaskSpec only
        if the object is genuinely LOST — sealed at least once, currently
        locationless — and nobody else claimed it recently. One winner
        per loss; an in-flight first execution is never duplicated."""
        with self._lock:
            if oid in self.directory or oid not in self._sealed_once:
                return None
            spec = self.lineage.get(oid)
            if spec is None:
                return None
            now = time.monotonic()
            t = self._reconstruct_claims.get(oid)
            if t is not None and now - t < claim_timeout_s:
                return None
            self._reconstruct_claims[oid] = now
            self._reconstruct_counts[oid] = (
                self._reconstruct_counts.get(oid, 0) + 1)
            # bounded audit trail: oldest rows fall off (claims are
            # rare — node deaths — but a long-lived head must not
            # accumulate a row per reconstructed object forever)
            while len(self._reconstruct_counts) > 4096:
                self._reconstruct_counts.pop(
                    next(iter(self._reconstruct_counts)))
            return spec

    def reconstruct_stats(self) -> Dict[str, int]:
        """Successful lineage-reconstruction claims per object (hex) —
        the claim gate's audit trail: the chaos tests assert each lost
        link of a produce->transform->consume chain was rebuilt exactly
        once."""
        with self._lock:
            return {oid.hex(): n
                    for oid, n in self._reconstruct_counts.items()}

    # --------------------------------------------------------- snapshots
    # Explicit copies for state queries: both the in-process plane and the
    # remote client expose these, so node.py never touches raw attributes.
    def actors_snapshot(self) -> List[Tuple[ActorID, ActorRecord]]:
        with self._lock:
            return list(self.actors.items())

    def jobs_snapshot(self) -> List[JobRecord]:
        with self._lock:
            return list(self.jobs.values())

    def gang_hosts(self) -> set:
        """Nodes holding live placement-group bundles. A gang node is
        never drainable while its PG exists — the reservation holds
        resources whether or not tasks currently run (reference: PG
        resources stay claimed until removal)."""
        out = set()
        with self._lock:
            for rec in self.placement_groups.values():
                if rec.get("state") == PG_CREATED:
                    out.update(rec.get("assignment") or ())
        return out

    def directory_snapshot(self) -> List[Tuple[ObjectID,
                                               Tuple[NodeID, ObjectMeta]]]:
        with self._lock:
            return list(self.directory.items())

    def pgs_snapshot(self) -> List[Tuple[PlacementGroupID, dict]]:
        with self._lock:
            return list(self.placement_groups.items())

    # ------------------------------------------------------- stall detector
    # Reference analogue: the task-event stall warnings GcsTaskManager
    # derives from tasks stuck in a non-terminal state. The sweep runs
    # on the plane (it owns every diagnosis input: task events, the
    # directory, actor states, per-node availability) and is triggered
    # from the hosting node's tick; emission goes through that node's
    # EventLogger so stalls land in the events JSONL AND the ring.

    _STALL_PENDING_STATES = ("PENDING_ARGS_AVAIL",
                             "PENDING_NODE_ASSIGNMENT")

    def maybe_sweep_stalls(self, coll_probe=None) -> List[dict]:
        """Rate-limited sweep: flag tasks sitting in a pending state (or
        RUNNING) past the configured thresholds, each with a diagnosed
        *cause* — unsatisfiable resource shape, a never-ready dependency,
        a dead target actor, a collective wait that outlived half its
        timeout (``collective_stuck``, see below), or plain queue
        saturation. Returns the newly-diagnosed records; the caller
        emits them as WARNING cluster events.

        ``coll_probe`` (provided by the hosting node) takes a list of
        ``(TaskEvent, age_s)`` RUNNING candidates older than
        ``collective_timeout_s / 2`` and returns ``(ev, cause, message)``
        triples for the ones whose worker stack shows them parked in a
        collective wait. It fans out RPCs, so it runs strictly OUTSIDE
        the plane lock — candidates are gathered locked, probed
        unlocked, and de-duplicated through ``_stall_warned`` like every
        other cause."""
        interval = CONFIG.stall_detector_interval_s
        if interval <= 0:
            return []
        now = time.time()
        out: List[dict] = []
        coll_half = CONFIG.collective_timeout_s / 2.0
        coll_candidates: List[tuple] = []
        with self._lock:
            if now - self._stall_last_sweep < interval:
                return []
            self._stall_last_sweep = now
            latest: Dict[TaskID, TaskEvent] = {}
            for ev in self.task_events:
                latest[ev.task_id] = ev
            # entries for tasks evicted from the ring must not leak
            for tid in [t for t in self._stall_warned if t not in latest]:
                del self._stall_warned[tid]
            total: Dict[str, float] = {}
            avail: Dict[str, float] = {}
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources_total.items():
                    total[k] = total.get(k, 0.0) + v
                for k, v in (n.resources_available or {}).items():
                    avail[k] = avail.get(k, 0.0) + v
            n_pending = sum(1 for ev in latest.values()
                            if ev.state in self._STALL_PENDING_STATES)
            for tid, ev in latest.items():
                if ev.state in self._STALL_PENDING_STATES:
                    threshold = CONFIG.stall_pending_threshold_s
                elif ev.state == "RUNNING":
                    threshold = CONFIG.stall_running_threshold_s
                    age = now - ev.timestamp
                    if (coll_probe is not None and coll_half > 0
                            and age >= coll_half
                            and self._stall_warned.get(tid)
                            != "collective_stuck"):
                        # a collective wedges long before the generic
                        # RUNNING threshold (300s default vs timeout/2)
                        coll_candidates.append((ev, age))
                else:
                    self._stall_warned.pop(tid, None)
                    continue
                age = now - ev.timestamp
                if threshold <= 0 or age < threshold:
                    continue
                cause, message = self._diagnose_stall_locked(
                    ev, total, avail, n_pending, age, latest)
                if (cause == "slow_running" and self._stall_warned.get(
                        tid) == "collective_stuck"):
                    # collective_stuck is the more specific refinement
                    # of slow_running — don't flip-flop between them
                    continue
                if self._stall_warned.get(tid) == cause:
                    continue
                self._stall_warned[tid] = cause
                out.append({"message": message,
                            "task_id": tid.hex(),
                            "task_name": ev.name,
                            "task_state": ev.state,
                            "age_s": round(age, 1),
                            "cause": cause})
        if coll_candidates and coll_probe is not None:
            try:
                probed = coll_probe(coll_candidates) or []
            except Exception:   # noqa: BLE001 — diagnosis is best-effort
                probed = []
            for ev, cause, message in probed:
                with self._lock:
                    if self._stall_warned.get(ev.task_id) == cause:
                        continue
                    self._stall_warned[ev.task_id] = cause
                out.append({"message": message,
                            "task_id": ev.task_id.hex(),
                            "task_name": ev.name,
                            "task_state": ev.state,
                            "age_s": round(now - ev.timestamp, 1),
                            "cause": cause})
        return out

    def _diagnose_stall_locked(self, ev: TaskEvent, total: dict,
                               avail: dict, n_pending: int, age: float,
                               latest: Dict[TaskID, TaskEvent],
                               ) -> Tuple[str, str]:
        """Order matters: the most specific verifiable cause wins."""
        missing = [oid for oid in (ev.pending_args or ())
                   if oid not in self.directory]
        if missing:
            # an object whose producing task is still live is upstream
            # slowness, not loss — only claim "never created"/"lost"
            # when NO live producer exists for any missing dep
            for oid in missing:
                spec = self.lineage.get(oid)
                pev = latest.get(spec.task_id) if spec is not None else None
                if pev is not None and pev.state not in ("FINISHED",
                                                         "FAILED"):
                    return ("slow_producer",
                            f"task {ev.name!r} has waited {age:.0f}s for "
                            f"object {oid.hex()[:12]} still being "
                            f"produced by task {spec.name!r} "
                            f"({pev.state}) — upstream slowness, not "
                            "loss")
            never = [o for o in missing if o not in self._sealed_once]
            what = "never created" if never else "lost"
            oids = ", ".join(o.hex()[:12] for o in missing[:4])
            recon = ("" if any(o in self.lineage for o in missing)
                     else " and cannot be reconstructed (no lineage)")
            return ("blocked_object",
                    f"task {ev.name!r} has waited {age:.0f}s for "
                    f"object(s) {oids} that were {what}{recon}")
        res = ev.resources or {}
        if res:
            # per-NODE feasibility, not the summed cluster total: a
            # {CPU: 3} task on two 2-CPU nodes fits the sum but no node,
            # and will never schedule (matches scheduler.pick_node)
            alive = [n for n in self.nodes.values() if n.alive]
            fits_some = any(
                all(n.resources_total.get(k, 0.0) >= v
                    for k, v in res.items())
                for n in alive)
            if not fits_some:
                biggest = {k: max((n.resources_total.get(k, 0.0)
                                   for n in alive), default=0.0)
                           for k in res}
                return ("unsatisfiable_resources",
                        f"task {ev.name!r} demands {res} but no single "
                        f"node can satisfy it (largest per-resource "
                        f"capacities {biggest}, cluster total "
                        f"{ {k: total.get(k, 0.0) for k in res} }) — it "
                        "will never schedule")
        if ev.is_actor_task and ev.actor_id is not None:
            rec = self.actors.get(ev.actor_id)
            if rec is not None and rec.state == ACTOR_DEAD:
                reason = rec.death_reason or "no reason recorded"
                return ("actor_dead",
                        f"call {ev.name!r} targets dead actor "
                        f"{ev.actor_id.hex()[:12]} ({reason})")
        if ev.state == "RUNNING":
            return ("slow_running",
                    f"task {ev.name!r} has been RUNNING for {age:.0f}s "
                    "— inspect worker stacks with `rtpu stack` or "
                    "`rtpu profile`")
        return ("queue_saturation",
                f"task {ev.name!r} has been queued {age:.0f}s; its shape "
                f"fits the cluster but capacity hasn't freed (available "
                f"{avail}, {n_pending} task(s) pending) — queue "
                "saturation")

    # ------------------------------------------------------------- events
    def record_task_event(self, ev: TaskEvent) -> None:
        with self._lock:
            self.task_events.append(ev)

    def list_task_events(self, limit: int = 1000) -> List[TaskEvent]:
        with self._lock:
            return list(self.task_events)[-limit:]

    # --------------------------------------- structured events + spans
    def record_cluster_event(self, rec: dict) -> None:
        with self._lock:
            evicted = (self.cluster_events.maxlen is not None
                       and len(self.cluster_events)
                       == self.cluster_events.maxlen)
            if evicted:
                self._events_evicted += 1
            self.cluster_events.append(rec)
        if evicted:
            # outside the plane lock (counter_inc takes a telemetry
            # shard lock): silent ring loss is itself observable
            telemetry.counter_inc(M_EVENTS_EVICTED)

    def list_cluster_events(self, limit: int = 1000,
                            since: Optional[float] = None,
                            until: Optional[float] = None) -> List[dict]:
        with self._lock:
            rows = list(self.cluster_events)
        if since is not None:
            rows = [r for r in rows if (r.get("timestamp") or 0) >= since]
        if until is not None:
            rows = [r for r in rows if (r.get("timestamp") or 0) <= until]
        return rows[-limit:]

    def events_stats(self) -> dict:
        with self._lock:
            return {"buffered": len(self.cluster_events),
                    "capacity": self.cluster_events.maxlen,
                    "evicted": self._events_evicted}

    # -------------------------------------------- lifecycle transitions
    # concurrency: requires(gcs.plane)
    def _record_lifecycle_locked(self, kind: str, ident: str, state: str,
                                 **fields) -> None:
        rec = {"kind": kind, "id": ident, "state": state,
               "ts": time.time()}
        rec.update({k: v for k, v in fields.items() if v is not None})
        self.lifecycle_events.append(rec)

    def lifecycle_snapshot(self, limit: int = 10000,
                           since: Optional[float] = None) -> List[dict]:
        """Node/actor/PG state transitions, retained past death."""
        with self._lock:
            rows = list(self.lifecycle_events)
        if since is not None:
            rows = [r for r in rows if r["ts"] >= since]
        return rows[-limit:]

    def record_spans(self, spans: List[dict]) -> None:
        with self._lock:
            self.spans.extend(spans)

    def list_spans(self, limit: int = 10000) -> List[dict]:
        with self._lock:
            return list(self.spans)[-limit:]

    # ------------------------------------------------------------ metrics
    # concurrency: requires(gcs.plane)
    def _metric_series_ok(self, table: dict, key: tuple) -> bool:
        """Series-cardinality cap: a runaway tag (e.g. a per-request id)
        must not grow the head without bound."""
        if key in table:
            return True
        if (len(self.metrics_counters) + len(self.metrics_gauges)
                + len(self.metrics_hists)
                + len(self.metrics_digests)) >= CONFIG.metric_series_limit:
            self._metrics_dropped_keys.add(key)
            return False
        return True

    def record_metrics(self, payload: dict) -> None:
        """Merge one process's telemetry deltas (counters += delta,
        gauges latest-timestamp-wins, histogram buckets elementwise)."""
        with self._lock:
            for name, m in (payload.get("meta") or {}).items():
                existing = self.metrics_meta.get(name)
                if existing is None:
                    self.metrics_meta[name] = dict(m)
                elif m.get("description") and not existing.get("description"):
                    existing["description"] = m["description"]
            for key, delta in (payload.get("counters") or {}).items():
                if self._metric_series_ok(self.metrics_counters, key):
                    self.metrics_counters[key] = (
                        self.metrics_counters.get(key, 0.0) + delta)
            for key, vt in (payload.get("gauges") or {}).items():
                if vt[0] != vt[0]:
                    # NaN delete marker (telemetry.gauge_delete): the
                    # series' subject is gone — forget the series
                    # instead of exporting the marker, and tombstone
                    # the key so an older in-flight publish can't
                    # re-insert it
                    self.metrics_gauges.pop(key, None)
                    self._gauge_tombstones[key] = max(
                        vt[1], self._gauge_tombstones.get(key, 0.0))
                    if len(self._gauge_tombstones) > 1024:
                        for k in sorted(self._gauge_tombstones,
                                        key=self._gauge_tombstones.get
                                        )[:512]:
                            del self._gauge_tombstones[k]
                    continue
                dead_ts = self._gauge_tombstones.get(key)
                if dead_ts is not None:
                    if vt[1] <= dead_ts:
                        continue            # straggler from a retiree
                    del self._gauge_tombstones[key]   # re-created
                if not self._metric_series_ok(self.metrics_gauges, key):
                    continue
                old = self.metrics_gauges.get(key)
                if old is None or vt[1] >= old[1]:
                    self.metrics_gauges[key] = tuple(vt)
            for key, h in (payload.get("hists") or {}).items():
                if not self._metric_series_ok(self.metrics_hists, key):
                    continue
                cur = self.metrics_hists.get(key)
                if cur is None:
                    self.metrics_hists[key] = {
                        "buckets": tuple(h["buckets"]),
                        "counts": list(h["counts"]),
                        "sum": float(h["sum"]), "count": int(h["count"]),
                        "exemplar": h.get("exemplar")}
                elif cur["buckets"] == tuple(h["buckets"]):
                    cur["counts"] = [a + b for a, b in
                                     zip(cur["counts"], h["counts"])]
                    cur["sum"] += h["sum"]
                    cur["count"] += h["count"]
                    if h.get("exemplar") is not None:
                        cur["exemplar"] = h["exemplar"]
                else:
                    # same name+tags, different boundaries: buckets can't
                    # merge — keep the first layout, fold into sum/count
                    # so totals stay right, and count the conflict
                    cur["sum"] += h["sum"]
                    cur["count"] += h["count"]
                    cur["counts"][-1] += int(h["count"])
                    self._metrics_conflict_keys.add(key)
            for key, d in (payload.get("digests") or {}).items():
                if self._metric_series_ok(self.metrics_digests, key):
                    self.metrics_digests[key] = \
                        telemetry.merge_digest_payloads(
                            self.metrics_digests.get(key), d)
                    if (self.metrics_history.enabled
                            and CONFIG.metrics_history_capacity > 0):
                        # interval accumulator for the history plane: a
                        # frame's quantiles cover the frame's WINDOW
                        # (cumulative digests can't be subtracted)
                        cur = self._history_interval_digests.get(key)
                        self._history_interval_digests[key] = (
                            telemetry.merge_digest_payloads(cur, d)
                            if cur else dict(d))

    def record_history_snapshot(self) -> Optional[int]:
        """One metrics-history tick (triggered from the plane-hosting
        node's tick loop, self-rate-limited to the finest level step
        like the stall/leak sweeps): append the merge table's current
        values plus the accumulated interval digests as a frame.
        Returns the ring's estimated byte total, or ``None`` when
        rate-limited/disabled."""
        # the live CONFIG check (beside the ring's init-time flag) lets
        # an A/B toggle retention off in-process (bench_telemetry's
        # history_ab gate measures exactly this knob)
        if not (self.metrics_history.enabled
                and CONFIG.metrics_history_capacity > 0):
            return None
        now = time.time()
        with self._lock:
            finest = self.metrics_history.levels[0].step
            if now - self._history_last < finest:
                return None
            self._history_last = now
            counters = dict(self.metrics_counters)
            gauges = {k: v[0] for k, v in self.metrics_gauges.items()}
            hists = {k: (h["count"], h["sum"])
                     for k, h in self.metrics_hists.items()}
            interval = self._history_interval_digests
            self._history_interval_digests = {}
            return self.metrics_history.record(now, counters, gauges,
                                               hists, interval)

    def metrics_history_query(self, name: Optional[str] = None,
                              tags: Optional[dict] = None,
                              window: Optional[float] = None,
                              step: Optional[float] = None) -> dict:
        """Windowed aligned series from the retention ring (the
        ``state.metrics_history()`` backend). The plane lock covers only
        the cheap frame-ref snapshot; conversion/filtering of hundreds
        of frames runs OUTSIDE it (frames are immutable once appended),
        so doctor/dashboard/trend queries never stall scheduling."""
        with self._lock:
            snap = self.metrics_history.level_snapshot()
            enabled = self.metrics_history.enabled
        return history_mod.query_levels(snap, enabled, name=name,
                                        tags=tags, window=window,
                                        step=step)

    def metrics_history_dump(self) -> dict:
        """Whole-ring dump for debug bundles (offline replay); same
        snapshot-then-convert-unlocked shape as the query path."""
        with self._lock:
            snap = self.metrics_history.level_snapshot()
            enabled = self.metrics_history.enabled
            total = self.metrics_history.total_bytes
            evicted = self.metrics_history.frames_evicted
        return history_mod.dump_levels(snap, enabled, total, evicted)

    def metrics_snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.metrics_counters),
                "gauges": dict(self.metrics_gauges),
                "hists": {k: {**v, "counts": list(v["counts"])}
                          for k, v in self.metrics_hists.items()},
                "digests": {k: dict(v)
                            for k, v in self.metrics_digests.items()},
                "meta": {k: dict(v) for k, v in self.metrics_meta.items()},
                "dropped_series": (len(self._metrics_dropped_keys)
                                   + len(self._metrics_conflict_keys)),
            }

    # ------------------------------------------------------------- pubsub
    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        """In-process pubsub (reference analogue: ``src/ray/pubsub/`` long-poll
        channels). Callbacks run on the publisher's thread; keep them cheap."""
        with self._lock:
            self._subscribers.setdefault(channel, []).append(callback)

    def publish(self, channel: str, payload: Any) -> None:
        with self._lock:
            subs = list(self._subscribers.get(channel, ()))
        for cb in subs:
            try:
                cb(payload)
            except Exception:
                pass
