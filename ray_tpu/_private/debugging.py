"""On-demand distributed debugging: thread dumps + sampling profiler.

Equivalent role to the reference's ``ray stack`` (``scripts.py`` shelling
out to py-spy over every worker pid) and its profiling hooks
(``_private/profiling.py``). Both capabilities here are pure-Python and
in-process: a worker/driver answers a ``STACK_DUMP`` frame with
``sys._current_frames()`` walked into faulthandler-style per-thread
traces, and a ``PROFILE_START`` frame starts a bounded background
sampler whose output is flamegraph-compatible collapsed stacks plus
per-thread leaf segments convertible to a Chrome trace. Collection fans
out over the existing node RPC plane (see ``node.collect_local_stacks``
/ ``node.cluster_stacks``); cross-node dedup lives in
``gcs.aggregate_stacks``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

# Runtime plumbing threads excluded from profiles by default: they sit
# in recv()/wait() and would drown task code in idle samples. Stack
# DUMPS always include them (a wedged flusher is exactly what a dump
# must show); only the sampler filters.
RUNTIME_THREADS = frozenset({
    "MainThread",               # worker main loop = socket reader
    "rtpu-client-reader",
    "rtpu-ref-flusher",
    "rtpu-telemetry-flush",
    "rtpu-telemetry-sampler",
    "rtpu-dash-history",
})


def _short_path(path: str) -> str:
    parts = path.replace(os.sep, "/").rsplit("/", 2)
    return "/".join(parts[-2:]) if len(parts) > 1 else path


def _format_stack(frame) -> List[str]:
    """Frames of one thread, outermost first (faulthandler order), each
    ``func (dir/file.py:line)``."""
    out: List[str] = []
    while frame is not None:
        code = frame.f_code
        out.append(f"{code.co_name} "
                   f"({_short_path(code.co_filename)}:{frame.f_lineno})")
        frame = frame.f_back
    out.reverse()
    return out


def thread_stacks() -> List[dict]:
    """All live threads of THIS process via ``sys._current_frames()``."""
    names: Dict[int, Tuple[str, bool]] = {
        t.ident: (t.name, t.daemon) for t in threading.enumerate()
        if t.ident is not None}
    out = []
    for tid, frame in sys._current_frames().items():
        name, daemon = names.get(tid, (f"tid-{tid}", True))
        out.append({"thread_id": tid, "thread_name": name,
                    "daemon": daemon, "frames": _format_stack(frame)})
    out.sort(key=lambda d: (d["thread_name"] != "MainThread",
                            d["thread_name"]))
    return out


def collect_stack_dump(kind: str = "process", **ids) -> dict:
    """One process's stack dump record (the ``STACK_DUMP`` reply body).
    ``ids`` carries identity tags (worker_id, node_id, ...). The dump
    also names the task currently executing in this process (best-
    effort read of the execution context from the reader thread) so a
    control-plane diagnosis — e.g. the stall detector's
    ``collective_stuck`` probe — can map a stalled task to its worker's
    stack without a worker registry round trip."""
    from . import context
    out = {"kind": kind, "pid": os.getpid(), "timestamp": time.time(),
           "threads": thread_stacks(), **ids}
    tid = getattr(context, "current_task_id", None)
    if tid is not None:
        try:
            out.setdefault("task_id", tid.hex())
            out.setdefault("task_name",
                           getattr(context, "current_task_name", None))
        except Exception:   # noqa: BLE001 — identity tags are optional
            pass
    return out


def format_stack_dump(dump: dict) -> str:
    """Human-readable rendering of one dump (CLI / logs)."""
    who = dump.get("worker_id") or dump.get("node_id") or "?"
    lines = [f"--- {dump.get('kind', 'process')} {str(who)[:12]} "
             f"pid={dump.get('pid')} ---"]
    for th in dump.get("threads", []):
        lines.append(f"  Thread {th['thread_name']} "
                     f"(id={th['thread_id']}"
                     f"{', daemon' if th.get('daemon') else ''}):")
        for fr in th.get("frames", []):
            lines.append(f"    {fr}")
    return "\n".join(lines)


# ------------------------------------------------------ sampling profiler

def run_profile(duration_s: float, interval_ms: float = 10,
                task_filter: Optional[str] = None,
                exclude_threads: frozenset = RUNTIME_THREADS) -> dict:
    """Sample this process's threads for ``duration_s`` at
    ``interval_ms``. Wall-clock sampling: a thread blocked in get() or a
    collective accrues samples exactly where it waits, which is the
    point. Output:

    - ``collapsed``: {"f1;f2;f3": count} — flamegraph collapsed-stack
      format (``flamegraph.pl``/speedscope-compatible once written as
      ``stack count`` lines).
    - ``segments``: [[thread_name, leaf_frame, start_ts, end_ts], ...] —
      consecutive same-leaf samples merged; feeds ``chrome_trace()``.

    ``task_filter`` only records samples taken while this worker's
    current task name contains the substring (best-effort for
    max_concurrency>1 actors: the marker is process-global).
    """
    from . import context

    interval = max(float(interval_ms), 1.0) / 1000.0
    deadline = time.monotonic() + max(float(duration_s), 0.05)
    collapsed: Dict[str, int] = {}
    open_segs: Dict[int, list] = {}      # tid -> [name, leaf, start, end]
    segments: List[list] = []
    own = threading.get_ident()
    num_samples = 0
    while time.monotonic() < deadline:
        ts = time.time()
        if task_filter is not None:
            current = getattr(context, "current_task_name", None)
            if not current or task_filter not in current:
                # close open segments: a matching task resuming later
                # with the same leaf must not extend a span across the
                # filtered-out gap in the Chrome trace
                segments.extend(open_segs.values())
                open_segs.clear()
                time.sleep(interval)
                continue
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.ident is not None}
        for tid, frame in sys._current_frames().items():
            if tid == own:
                continue
            name = names.get(tid, f"tid-{tid}")
            if name in exclude_threads or name.startswith("rtpu-debug"):
                continue
            frames = _format_stack(frame)
            if not frames:
                continue
            key = ";".join(frames)
            collapsed[key] = collapsed.get(key, 0) + 1
            leaf = frames[-1]
            seg = open_segs.get(tid)
            if seg is not None and seg[1] == leaf:
                seg[3] = ts
            else:
                if seg is not None:
                    segments.append(seg)
                open_segs[tid] = [name, leaf, ts, ts]
        num_samples += 1
        time.sleep(interval)
    segments.extend(open_segs.values())
    return {"duration_s": float(duration_s),
            "interval_ms": float(interval_ms),
            "num_samples": num_samples,
            "task_filter": task_filter,
            "collapsed": collapsed,
            "segments": segments}


def profile_async(conn, token: int, opts: dict, **ids) -> None:
    """Worker-side ``PROFILE_START`` handler: run the sampler on a
    background thread and ship the report back as ``PROFILE_REPORT``.
    Never blocks the caller (the connection reader thread)."""
    from . import protocol as P

    def run():
        try:
            report = run_profile(
                float(opts.get("duration_s", 5.0)),
                float(opts.get("interval_ms", 10)),
                opts.get("task_filter"))
            report.update(ids)
        except Exception:   # noqa: BLE001 — debugging must not kill work
            report = None
        try:
            conn.send((P.PROFILE_REPORT, (token, report)))
        except OSError:
            pass

    threading.Thread(target=run, daemon=True,
                     name="rtpu-debug-profiler").start()


def merge_collapsed(reports: List[dict]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for rep in reports or []:
        for stack, count in (rep.get("collapsed") or {}).items():
            out[stack] = out.get(stack, 0) + count
    return out


def top_stacks(collapsed: Dict[str, int], n: int = 10) -> List[tuple]:
    """Most-sampled stacks, (count, [frames]) descending."""
    ranked = sorted(collapsed.items(), key=lambda kv: -kv[1])[:n]
    return [(count, stack.split(";")) for stack, count in ranked]


def write_collapsed(collapsed: Dict[str, int], path: str) -> None:
    """``stack count`` lines — feed to flamegraph.pl / speedscope."""
    with open(path, "w") as f:
        for stack, count in sorted(collapsed.items(),
                                   key=lambda kv: -kv[1]):
            f.write(f"{stack} {count}\n")


def chrome_trace(reports: List[dict]) -> List[dict]:
    """Chrome-trace JSON (chrome://tracing / Perfetto) from per-worker
    sample segments: one X event per run of identical leaf frames."""
    trace = []
    for rep in reports or []:
        pid = (f"worker:{str(rep.get('worker_id', '?'))[:8]}"
               + (f"@{str(rep.get('node_id', ''))[:8]}"
                  if rep.get("node_id") else ""))
        interval_s = float(rep.get("interval_ms", 10)) / 1000.0
        for name, leaf, start, end in rep.get("segments", []):
            trace.append({
                "name": leaf, "cat": "sample", "ph": "X",
                "ts": start * 1e6,
                "dur": max(end - start, interval_s) * 1e6,
                "pid": pid, "tid": name, "args": {},
            })
    return trace
