"""CoreClient: the per-process runtime connecting to the node service.

Equivalent role to the reference's worker-side ``CoreWorker``
(``src/ray/core_worker/core_worker.h:285`` — Submit/Get/Put/Wait) plus the
Cython binding (``python/ray/_raylet.pyx:2947``). One instance per process:
the driver creates one in ``init()``; every worker process creates one at
registration. Request/reply correlation lives here; object payloads are
loaded zero-copy through ``ObjectReader``.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import exceptions
from . import context as _ctx
from . import fieldsan
from . import locksan
from . import protocol as P
from . import telemetry
from .config import CONFIG
from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID, WorkerID
from .object_ref import ObjectRef, ObjectRefGenerator
from .object_store import ObjectMeta, ObjectReader, create_segment
from . import serialization as ser


def _flat_bytes(smeta, views, total: int) -> bytes:
    """Write the (meta, buffers) wire format into one contiguous blob."""
    out = bytearray(total)
    ser.write_to(memoryview(out), smeta, views)
    return bytes(out)


# creation-callsite capture (reference analogue: the ReferenceCounter's
# per-ref callsites behind RAY_record_ref_creation_sites): the frame
# walk skips everything inside the ray_tpu package so a data-plane
# helper's internal put() is attributed to the user line that drove it
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep


def _callsite() -> str:
    """``dir/file.py:line`` of the nearest frame outside ray_tpu — a few
    ``f_back`` hops on the hot path; no ``inspect.stack()``, no file IO.
    Falls back to the innermost non-package-rooted form for calls with
    no user frame (runtime-internal puts)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_ROOT):
            parts = fn.split(os.sep)
            return f"{os.sep.join(parts[-2:])}:{f.f_lineno}"
        f = f.f_back
    return "<internal>"


def _creator_label() -> str:
    """Who is creating the object: the running task/actor-method name in
    a worker, else the driver."""
    name = _ctx.current_task_name
    return name if name else "driver"


@fieldsan.guarded
class CoreClient:
    def __init__(self, conn: P.Connection, job_id: JobID,
                 worker_id: WorkerID, kind: int):
        self.conn = conn
        self.job_id = job_id
        self.worker_id = worker_id
        self.kind = kind
        self.node_id = None         # set by driver init / worker runtime
        self.namespace = "default"  # set by init(namespace=...)
        # in-process NodeService when this driver runs on the head: large
        # puts then alloc/write/seal directly against the local store —
        # no ALLOC_OBJECT/PUT_OBJECT_SYNC round trips (reference
        # analogue: CoreWorker's local plasma client)
        self.local_node = None
        # Ray-Client-equivalent mode: this process shares no /dev/shm
        # with the node it is connected to, so object payloads must ride
        # the socket (set by init() when the head's host differs)
        self.wire_data_plane = False
        # worker runtime hooks: return unstarted leased tasks on block
        self.on_worker_block = None
        self.on_worker_unblock = None
        self.reader = ObjectReader()
        self._futures: Dict[int, Future] = {}
        self._req_lock = locksan.lock("client.req")
        self._next_req = 1
        conn.on_send_error = self._on_send_error
        self._registered_fns: set = set()
        self._reader_thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        # local reference counts per object; the node hears only the
        # 0→1 / 1→0 edges (reference: ``reference_count.h:61``).
        # ref_decr is called from ObjectRef.__del__, which cyclic GC may
        # run at ANY point — including while this thread already holds
        # _ref_lock — so decrements only append to a lock-free deque and
        # are applied under the lock by ref_incr or the flusher thread.
        # Edge order is captured by the shared buffer and batches leave
        # FIFO under _edge_flush_lock, so a register and a drop can
        # never reach the wire in inverted order (the socket write
        # itself stays OUT of _ref_lock — see flush_refs).
        self._ref_counts: Dict[ObjectID, int] = {}
        self._ref_lock = locksan.lock("client.ref")
        self._edge_flush_lock = locksan.lock("client.edge_flush")
        self._pending_decrs: "deque[ObjectID]" = deque()
        # creation provenance records (oid, callsite, creator), buffered
        # beside the edge stream and shipped as one OBJ_PROVENANCE frame
        # per flush (empty forever when object_callsite_enabled=0)
        self._prov_buf: List[tuple] = []
        # ordered edge stream, coalesced into one REF_BATCH frame — one
        # socket write per ~batch of submissions instead of one per ref.
        # Delayed registration is safe: an object only becomes freeable
        # once tracked, and tracking starts when the batch lands.
        self._edge_buf: List[Tuple[int, ObjectID]] = []
        self._flusher: Optional[threading.Thread] = None
        # Submission buffer: task/actor-call specs coalesce into one
        # SUBMIT_BATCH frame per flush — one pickle header + one syscall
        # + one dispatcher wakeup for a burst instead of one each
        # (reference analogue: the Cython submit path amortizes via the
        # C++ submit queue). Flushed before ANY other frame leaves this
        # client, so cross-op ordering is exactly the unbatched order.
        self._sub_buf: List[Tuple[int, Any]] = []
        self._sub_lock = locksan.lock("client.sub")
        # streaming-generator producer credit: {task_id: [consumed, Event]}
        # updated by GEN_ACK pushes; the executing thread waits on the
        # Event when its in-flight window fills
        self._gen_credit: Dict[TaskID, list] = {}
        self._gen_credit_lock = locksan.lock("client.gen_credit")

    # ------------------------------------------------------------ refcounts
    def ref_incr(self, oid: ObjectID) -> None:
        flush = False
        with self._ref_lock:
            self._apply_decrs_locked()
            n = self._ref_counts.get(oid, 0)
            self._ref_counts[oid] = n + 1
            if n == 0:
                self._edge_buf.append((P.REF_REGISTER, oid))
            flush = len(self._edge_buf) >= 256
        if flush:
            self.flush_refs()
        self._ensure_flusher()

    def ref_decr(self, oid: ObjectID) -> None:
        # GC-safe: deque.append is atomic and takes no lock
        self._pending_decrs.append(oid)

    def _note_provenance(self, oids: Sequence[ObjectID]) -> None:
        """Record the creation callsite for freshly-minted object ids
        (puts, task/actor-call returns, actor creation returns). One
        frame walk per call covers the whole id batch."""
        if not oids or not CONFIG.object_callsite_enabled:
            return
        cs = _callsite()
        creator = _creator_label()
        with self._ref_lock:
            for oid in oids:
                self._prov_buf.append((oid, cs, creator))
        telemetry.counter_inc(telemetry.M_OBJ_CALLSITES, float(len(oids)))

    # concurrency: requires(client.ref)
    def _apply_decrs_locked(self) -> None:
        while True:
            try:
                oid = self._pending_decrs.popleft()
            except IndexError:
                return
            n = self._ref_counts.get(oid, 0) - 1
            if n <= 0:
                self._ref_counts.pop(oid, None)
                self._edge_buf.append((P.REF_DROP, oid))
            else:
                self._ref_counts[oid] = n

    def flush_refs(self) -> None:
        """Synchronously emit buffered ref edges. Called at ordering
        boundaries: a worker flushes BEFORE sending TASK_DONE so borrows
        registered during execution land while the task's arg pins still
        hold; a driver flushes after get() so refs unpickled out of a
        returned value are registered promptly.

        The socket write happens OUTSIDE ``_ref_lock`` (it used to be
        inside, serializing every concurrent ``.remote()`` caller's
        ref_incr behind a peer's flush — measured as the top non-wait
        cost of n_n driver threads). Wire order is still exact: edge
        ORDER lives in the shared buffer, and ``_edge_flush_lock`` —
        held across take-and-send — keeps batches FIFO, so a register
        and a drop can never reach the wire inverted."""
        with self._edge_flush_lock:
            with self._ref_lock:
                self._apply_decrs_locked()
                if self._closed.is_set():
                    self._edge_buf.clear()
                    self._prov_buf.clear()
                    return
                batch, self._edge_buf = self._edge_buf, []
                prov, self._prov_buf = self._prov_buf, []
            if batch:
                try:
                    self._send(P.REF_BATCH, batch)  # lint: allow-under-lock(edge_flush exists to serialize take-and-send; FIFO wire order is the invariant)
                except OSError:
                    pass
        if prov:
            # provenance is order-independent of the edge stream (a
            # pure per-oid attribution table), so it ships OUTSIDE the
            # flush lock — no new blocking work under any lock
            try:
                self._send(P.OBJ_PROVENANCE, prov)
            except OSError:
                pass

    def _ensure_flusher(self) -> None:
        if self._flusher is not None and self._flusher.is_alive():
            return
        t = threading.Thread(target=self._flush_loop,
                             name="rtpu-ref-flusher", daemon=True)
        self._flusher = t
        t.start()

    def _flush_loop(self) -> None:
        # 50ms cadence bounds the latency of a fire-and-forget
        # submission that is never followed by a blocking op
        while not self._closed.wait(0.05):
            try:
                self.flush_submissions()
            except OSError:
                pass
            if self._pending_decrs or self._edge_buf or self._prov_buf:
                self.flush_refs()
        try:
            self.flush_submissions()
        except OSError:
            pass
        self.flush_refs()

    def _active_namespace(self) -> str:
        """Task-context namespace if set (worker executing a task), else
        this client's (driver) namespace — so nested submissions keep
        propagating the driver's namespace at any depth."""
        from . import context
        ns = context.current_namespace.get()
        return ns if ns is not None else self.namespace

    # ------------------------------------------------------------ lifecycle
    def start_reader(self) -> None:
        """Driver mode: own the receive loop. Workers route replies here
        from their main loop instead."""
        t = threading.Thread(target=self._read_loop, name="rtpu-client-reader",
                             daemon=True)
        t.start()
        self._reader_thread = t

    def _read_loop(self) -> None:
        while True:
            # burst receive: the node's writer coalesces replies/pushes,
            # so one wakeup often resolves a whole batch of futures
            msgs = self.conn.recv_many()
            if msgs is None:
                self._fail_all(ConnectionError("lost connection to node"))
                return
            for msg in msgs:
                self.handle_message(*msg)

    def _take_future(self, req_id: int) -> Optional[Future]:
        """Pop a reply future UNDER ``_req_lock``: the reader thread's
        pop used to race ``_fail_all`` (conn teardown / send-error on
        another thread), whose take-all-and-clear could hand the SAME
        future to both sides — set_result after set_exception raises
        InvalidStateError and killed the process's only reply-routing
        loop. dict.pop alone looked atomic; the snapshot in _fail_all
        is what made it a two-step race (found by fieldsan, ISSUE 15)."""
        with self._req_lock:
            return self._futures.pop(req_id, None)

    def handle_message(self, op: int, payload: Any) -> None:
        if op == P.PUT_REPLY:
            (req_id,) = payload
            fut = self._take_future(req_id)
            if fut is not None:
                fut.set_result(None)
        elif op in (P.GET_REPLY, P.KV_REPLY, P.NAMED_ACTOR_REPLY,
                    P.FUNCTION_REPLY, P.INFO_REPLY):
            req_id, value = payload
            fut = self._take_future(req_id)
            if fut is not None:
                fut.set_result(value)
        elif op == P.WAIT_REPLY:
            req_id, ready, pending = payload
            fut = self._take_future(req_id)
            if fut is not None:
                fut.set_result((ready, pending))
        elif op == P.ERROR_REPLY:
            req_id, err = payload
            fut = self._take_future(req_id)
            if fut is not None:
                fut.set_exception(ser.from_bytes(err))
        elif op == P.GEN_ACK:
            task_id, consumed = payload
            with self._gen_credit_lock:
                # Normal acks are update-only: production acks can't
                # precede gen_credit_init (items ship after it), and
                # creating on a late ack would leak one entry per stream
                # in a pooled worker. The synthetic INFINITE credit of an
                # early GEN_CLOSE is the exception — it may arrive before
                # the task even starts, and must survive until init's
                # setdefault finds it (gen_credit_drop then removes it).
                ev = self._gen_credit.get(task_id)
                if ev is None and consumed >= (1 << 62):
                    ev = self._gen_credit[task_id] = [consumed,
                                                      threading.Event()]
                elif ev is not None and consumed > ev[0]:
                    ev[0] = consumed
                if ev is not None:
                    ev[1].set()
        elif op == P.COLL_DELIVER:
            # collective chunk for a rank in this process: deposit on
            # THIS (reader) thread — the rank thread blocked in
            # coll_transport.wait() wakes under the condition variable
            from . import coll_transport
            coll_key, data = payload
            coll_transport.deposit(tuple(coll_key), data)
        elif op == P.STACK_DUMP:
            # answered from THIS (reader) thread on purpose: it is never
            # the one blocked in user code, so a process wedged in get()
            # still reports every thread's stack (reference: `ray stack`)
            from . import debugging
            try:
                dump = debugging.collect_stack_dump(
                    kind=("worker" if self.kind == P.KIND_WORKER
                          else "driver"),
                    worker_id=self.worker_id.hex())
                self.conn.send((P.STACK_REPLY, (payload, dump)))
            except Exception:   # noqa: BLE001 — debugging is best-effort
                pass
        elif op == P.COLL_PROGRESS:
            # flight-recorder watermark query, answered on THIS (reader)
            # thread like STACK_DUMP: the rank thread may be wedged
            # inside the very collective being diagnosed
            from . import flight_recorder
            try:
                snap = flight_recorder.progress_snapshot(
                    kind=("worker" if self.kind == P.KIND_WORKER
                          else "driver"),
                    worker_id=self.worker_id.hex())
                self.conn.send((P.COLL_PROGRESS_REPLY, (payload, snap)))
            except Exception:   # noqa: BLE001 — debugging is best-effort
                pass
        elif op == P.PROFILE_START:
            # guarded like STACK_DUMP: an exception here (malformed
            # payload, can't-start-thread) would kill this process's
            # only message-receive loop
            try:
                token, opts = payload
                from . import debugging
                debugging.profile_async(self.conn, token,
                                        dict(opts or {}),
                                        worker_id=self.worker_id.hex())
            except Exception:   # noqa: BLE001 — debugging is best-effort
                pass
        elif op == P.EVENT:
            channel, data = payload
            if channel == "LOG" and self.kind == P.KIND_DRIVER:
                self._print_remote_logs(data)
        elif op == P.SHUTDOWN:
            self._fail_all(ConnectionError("node shutting down"))

    @staticmethod
    def _print_remote_logs(data: dict) -> None:
        """Worker output on the driver's stdout, prefixed like the
        reference's ``(pid=..., ip=...)`` log prefixes. tqdm magic
        lines render as in-place progress instead (reference:
        ``experimental/tqdm_ray.py``)."""
        import sys as _sys

        from ..util import tqdm_ray
        # a labelled worker (serve replica: "deployment#tag") prints its
        # human name — `rtpu logs` / driver output greps by deployment
        who = data.get("label") or data.get("worker", "?")[:8]
        prefix = f"(worker {who} " \
                 f"node={data.get('node_id', '?')[:8]})"
        plain = [line for line in data.get("lines", ())
                 if not tqdm_ray.render_magic_line(line)]
        if plain:
            out = "".join(f"{prefix} {line}\n" for line in plain)
            _sys.stdout.write(out)
            _sys.stdout.flush()

    def _fail_all(self, exc: Exception) -> None:
        # _req_lock orders this against _request: a request registered
        # before the lock is failed here; one after it sees _closed set
        # and raises instead of registering an unresolvable future.
        with self._req_lock:
            self._closed.set()
            futures = list(self._futures.values())
            self._futures.clear()
        for fut in futures:
            if not fut.done():
                fut.set_exception(exc)

    def close(self) -> None:
        # push out buffered fire-and-forget submissions before tearing
        # down the socket — a side-effecting task submitted just before
        # shutdown() must still reach the node
        try:
            self.flush_submissions()
        except OSError:
            pass
        self._closed.set()
        self.reader.close()
        self.conn.close()

    # ------------------------------------------------------------- plumbing
    def _on_send_error(self, msg, exc: BaseException) -> None:
        P.fail_dropped_request(msg, exc, self._req_lock, self._futures)

    def _request(self, op: int, make_payload) -> Future:
        fut: Future = Future()
        with self._req_lock:
            if self._closed.is_set():
                raise ConnectionError("connection to node is closed")
            req_id = self._next_req
            self._next_req += 1
            self._futures[req_id] = fut
        self.flush_submissions()
        self.conn.send((op, make_payload(req_id)))
        return fut

    def _send(self, op: int, payload: Any) -> None:
        self.flush_submissions()
        self.conn.send((op, payload))

    def _send_submission(self, op: int, payload: Any) -> None:
        """Queue a task/actor-call submission for the next batch flush.
        A full buffer flushes inline; otherwise the ref-flusher thread or
        the next blocking op flushes within its cadence."""
        with self._sub_lock:
            self._sub_buf.append((op, payload))
            n = len(self._sub_buf)
        if n >= CONFIG.submit_batch_max_specs:
            self.flush_submissions()
        else:
            self._ensure_flusher()

    def gen_next(self, task_id: TaskID, index: int):
        """Consumer side: block until item ``index`` of a streaming task
        is available; returns ("item", meta) | ("end", count) |
        ("error", err_bytes)."""
        fut = self._request(P.GEN_NEXT, lambda rid: (rid, task_id, index))
        return self._blocking_result(fut)

    def gen_close(self, task_id: TaskID) -> None:
        self._send(P.GEN_CLOSE, (task_id,))

    def gen_credit_init(self, task_id: TaskID) -> None:
        """Register the credit slot BEFORE the first item ships: acks
        may arrive before the producer's first wait, and dropping them
        would deadlock a producer at exactly ``window`` items."""
        with self._gen_credit_lock:
            self._gen_credit.setdefault(task_id, [0, threading.Event()])

    def gen_wait_credit(self, task_id: TaskID, produced: int,
                        window: int) -> None:
        """Producer-side backpressure: block until the consumer has
        acked enough items that fewer than ``window`` are in flight.
        GEN_ACK pushes (handled on the worker's recv thread) advance the
        credit."""
        if window <= 0:
            return
        while not self._closed.is_set():
            with self._gen_credit_lock:
                ent = self._gen_credit.get(task_id)
                if ent is None or produced - ent[0] < window:
                    return
                ent[1].clear()
            ent[1].wait(timeout=1.0)

    def gen_credit_drop(self, task_id: TaskID) -> None:
        with self._gen_credit_lock:
            self._gen_credit.pop(task_id, None)

    def flush_submissions(self) -> None:
        # send while holding the lock: a concurrent later submission must
        # not reach the socket before this batch (actor per-submitter
        # order rides frame order)
        with self._sub_lock:
            if not self._sub_buf:
                return
            batch, self._sub_buf = self._sub_buf, []
            if len(batch) == 1:
                self.conn.send(batch[0])  # lint: allow-under-lock(a later submission must not reach the socket before this batch; actor per-submitter order rides frame order)
            else:
                self.conn.send((P.SUBMIT_BATCH, batch))  # lint: allow-under-lock(same FIFO invariant as the single-spec branch)

    # ------------------------------------------------------------- objects
    def put(self, value: Any) -> ObjectRef:
        from .object_ref import begin_ref_capture, end_ref_capture
        oid = ObjectID.for_put(self.worker_id)
        # the ref exists (and is registered) BEFORE any contained-ref
        # pin references it as holder — see _pin_contained below
        ref = ObjectRef(oid)
        self._note_provenance((oid,))
        begin_ref_capture()
        try:
            if self.wire_data_plane:
                flat = self._serialize_flat(value)
            else:
                meta, sealed = self._store_value(oid, value)
        finally:
            contained = end_ref_capture()
        self._pin_contained(oid, contained)
        if self.wire_data_plane:
            self._wire_put(oid, *flat)
            return ref
        if sealed:
            pass    # adopted + published in-process (head driver)
        elif meta.shm_name is not None or meta.arena_ref is not None:
            # Large object: block until the node store adopts it — a
            # returned ref IS sealed, matching the reference
            # (``core_worker.cc:1141``). A one-way seal was measured at
            # <3% on the put bench and let a returned ref race the
            # store's visibility/accounting; not worth the drift.
            self._sync_put(meta)
        else:
            self._send(P.PUT_OBJECT, meta)
        return ref

    def _pin_contained(self, oid: ObjectID, contained: list) -> None:
        """Refs pickled INSIDE a stored value would lose their last
        holder once the caller's own refs die (same deadlock class as
        refs nested in task returns): ship the containment edge so the
        plane pins them until the container is freed. flush_refs first
        so our REGISTER of the container reaches the plane before the
        pin checks for a live holder."""
        if not contained:
            return
        self.flush_refs()
        self._send(P.RETURN_REFS, (oid, contained))

    def _sync_put(self, meta: ObjectMeta) -> None:
        """Acked put of a shm-backed object; unlinks the segment if the
        node rejects it, since no store owns it then. (Arena-backed
        objects need no cleanup here: the allocation is owned by the
        node store from the Create.)"""
        try:
            self._request(P.PUT_OBJECT_SYNC,
                          lambda rid: (rid, meta)).result()
        except BaseException:
            if meta.shm_name is not None:
                from multiprocessing import shared_memory
                try:
                    seg = shared_memory.SharedMemory(name=meta.shm_name)
                    seg.close()
                    seg.unlink()
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            raise

    def _store_value(self, oid: ObjectID, value: Any
                     ) -> Tuple[ObjectMeta, bool]:
        """Serialize a value; small inline, large into shm. Returns
        (meta, sealed) — sealed means the local fast path already
        adopted + published it and no PUT rpc is needed."""
        smeta, views = ser.serialize(value)
        total = ser.serialized_size(smeta, views)
        if total <= CONFIG.object_store_shm_threshold_bytes:
            return ObjectMeta(object_id=oid, size=total,
                              inline=_flat_bytes(smeta, views, total)), False
        meta = self._local_store_large(oid, smeta, views, total)
        if meta is not None:
            return meta, True
        return self.store_large(oid, smeta, views, total), False

    def _local_store_large(self, oid: ObjectID, smeta, views,
                           total: int) -> Optional[ObjectMeta]:
        """Head-driver fast path: the node service lives in THIS
        process, so allocate + write + seal directly against its store
        and publish the location — zero control-plane round trips for
        a large put (reference analogue: local plasma client)."""
        node = self.local_node
        if node is None or getattr(node, "dead", False):
            return None
        if CONFIG.object_store_lazy_put:
            try:
                meta = node.store.put_lazy(oid, smeta, views, total)
            except Exception:   # store unhealthy: RPC path decides
                return None
            if meta is not None:
                # zero bytes copied: the serialized views stay in this
                # process's heap until first cross-process demand (or
                # spill pressure) promotes them to the arena
                node._seal_object(meta)
                return meta
            return None         # duplicate put: RPC path decides
        try:
            buf, meta = node.store.create_local(oid, total)
        except Exception:       # store full / duplicate: RPC path decides
            return None
        try:
            ser.write_to(buf, smeta, views)
            node.store.seal(oid)
        except BaseException:
            # a failed fill (exporter error, KeyboardInterrupt) must not
            # leave a permanently unsealed, budget-charged entry behind
            del buf             # release the view before the arena/shm free
            node.store.abort_create(oid)
            raise
        node._seal_object(meta)     # re-adopt no-ops; publishes location
        return meta

    @staticmethod
    def _serialize_flat(value: Any) -> Tuple[bytes, int]:
        smeta, views = ser.serialize(value)
        total = ser.serialized_size(smeta, views)
        return _flat_bytes(smeta, views, total), total

    def _wire_put(self, oid: ObjectID, data: bytes, total: int) -> None:
        """Cross-host put: the payload rides the socket (out-of-band as
        a zero-copy iovec when large) and the NODE materializes it as
        the primary copy (we have no shared shm)."""
        if total <= CONFIG.object_store_shm_threshold_bytes:
            self._send(P.PUT_OBJECT,
                       ObjectMeta(object_id=oid, size=total, inline=data))
        else:
            self._request(P.PUT_OBJECT_WIRE,
                          lambda rid: (rid, oid, P.oob_wrap(data))).result()

    def store_large(self, oid: ObjectID, smeta, views,
                    total: int) -> ObjectMeta:
        """Write a large payload into shm: arena Create/Seal when the
        node store offers an arena slot (one mmap per process,
        ``native/object_arena.cpp``), else a dedicated segment."""
        from . import native
        if CONFIG.use_native_arena and native.available():
            try:
                ref = self._request(P.ALLOC_OBJECT,
                                    lambda rid: (rid, oid, total)).result()
            except Exception:
                ref = None
            if ref is not None:
                path, off = ref
                reader = native.ArenaReader.get(path)
                ser.write_to(reader.buffer(off, total), smeta, views)
                return ObjectMeta(object_id=oid, size=total,
                                  arena_ref=(path, off))
        seg = create_segment(oid, total)
        ser.write_to(seg.buf, smeta, views)
        name = seg.name
        seg.close()
        return ObjectMeta(object_id=oid, size=total, shm_name=name)

    @property
    def _get_op(self) -> int:
        return (P.GET_OBJECTS_FETCH if self.wire_data_plane
                else P.GET_OBJECTS)

    def _blocking_result(self, fut: Future):
        """Await a get/wait reply; a worker mid-task that actually has
        to WAIT tells its node first, so the node returns the task's CPU
        and the children being waited on can run (reference:
        ``NotifyDirectCallTaskBlocked`` — without this, nested
        submission deadlocks once parents hold every CPU). The short
        probe keeps already-ready gets off the notify path."""
        from . import context as _ctx
        in_task = (self.kind == P.KIND_WORKER
                   and _ctx.current_task_id is not None)
        if not in_task:
            return fut.result()
        try:
            return fut.result(timeout=0.004)
        except FuturesTimeout:
            pass
        if self.on_worker_block is not None:
            # hand back unstarted leased tasks BEFORE announcing the
            # block: they may be the very children this get() waits on
            self.on_worker_block()
        self._send(P.NOTIFY_BLOCKED, None)
        try:
            return fut.result()
        finally:
            self._send(P.NOTIFY_UNBLOCKED, None)
            if self.on_worker_unblock is not None:
                self.on_worker_unblock()

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        ids = [r.id for r in refs]
        fut = self._request(self._get_op,
                            lambda rid: (rid, ids, timeout))
        metas = self._blocking_result(fut)
        out = []
        for ref, m in zip(refs, metas):
            out.append(self._load_meta(ref, m, timeout))
        self.flush_refs()   # register refs unpickled from the values
        return out

    def _load_meta(self, ref: ObjectRef, meta: ObjectMeta,
                   timeout: Optional[float] = None) -> Any:
        # The owner may spill (and unlink) the segment between the meta
        # reply and our attach; a fresh GET restores it at the owning
        # store, so retry a couple of times before giving up. The retry
        # keeps the caller's timeout so get(timeout=...) stays bounded.
        for attempt in range(3):
            if meta is None:
                # lost between readiness and lookup (or the wire-fetch
                # payload vanished mid-copy); retry once, then surface
                if attempt == 2:
                    break
            else:
                try:
                    return self.reader.load(meta)
                except FileNotFoundError:
                    if attempt == 2:
                        raise
                    self.reader.release(meta.shm_name)
            meta = self._request(
                self._get_op,
                lambda rid: (rid, [ref.id], timeout)).result()[0]
        from ..exceptions import ObjectLostError
        raise ObjectLostError(ref.id, "object vanished during get()")

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        ids = [r.id for r in refs]
        fut = self._request(P.WAIT_OBJECTS,
                            lambda rid: (rid, ids, num_returns, timeout))
        ready_ids, pending_ids = self._blocking_result(fut)
        ready_set = set(ready_ids)
        ready = [r for r in refs if r.id in ready_set]
        pending = [r for r in refs if r.id not in ready_set]
        return ready, pending

    def free(self, refs: Sequence[ObjectRef]) -> None:
        ids = [r.id for r in refs]
        node = self.local_node
        if node is not None and not getattr(node, "dead", False):
            # head driver: free synchronously against the in-process
            # store (mirrors the local put fast path — a put loop that
            # frees as it goes must not outrun socket-borne frees and
            # push the store into spilling). Only ids the store has
            # already SEALED are eligible: an inline put rides the
            # socket as a fire-and-forget PUT_OBJECT, and an in-process
            # free must not overtake that queued frame (the
            # late-arriving put would resurrect the freed object) —
            # unsealed ids ride the same socket so the node applies put
            # and free in order.
            try:
                local = [oid for oid in ids if node.store.contains(oid)]
                if local:
                    for oid in local:
                        node.gcs.drop_location(oid)
                    node.store.free(local)
                    if len(local) == len(ids):
                        return
                    done = set(local)
                    ids = [oid for oid in ids if oid not in done]
            except Exception:   # noqa: BLE001 — fall back to the RPC
                pass
        self._send(P.FREE_OBJECTS, ids)

    def as_future(self, ref: ObjectRef) -> Future:
        out: Future = Future()

        def _attempt(attempts_left: int):
            def _resolve(fut: Future):
                try:
                    meta = fut.result()[0]
                    if meta is None:
                        from ..exceptions import ObjectLostError
                        if attempts_left > 0:
                            _attempt(attempts_left - 1)
                        else:
                            out.set_exception(ObjectLostError(
                                ref.id, "object vanished during get()"))
                        return
                    out.set_result(self.reader.load(meta))
                except FileNotFoundError:
                    # Segment spilled between reply and attach. This
                    # callback runs on the reply-routing thread, so retry
                    # asynchronously (a blocking re-request here would
                    # deadlock the thread that must process its reply).
                    if attempts_left > 0:
                        _attempt(attempts_left - 1)
                    else:
                        out.set_exception(
                            FileNotFoundError(f"object {ref.id} segment "
                                              "disappeared repeatedly"))
                except BaseException as e:  # noqa: BLE001
                    out.set_exception(e)

            inner = self._request(self._get_op,
                                  lambda rid: (rid, [ref.id], None))
            inner.add_done_callback(_resolve)

        _attempt(2)
        return out

    # ---------------------------------------------------------------- args
    def pack_args(self, args: tuple, kwargs: dict):
        packed = [self._pack_one(a) for a in args]
        pkw = {k: self._pack_one(v) for k, v in kwargs.items()}
        return packed, pkw

    def _pack_one(self, value: Any) -> Tuple[str, Any]:
        from .object_ref import begin_ref_capture, end_ref_capture
        if isinstance(value, ObjectRef):
            return ("r", value.id)
        begin_ref_capture()
        try:
            smeta, views = ser.serialize(value)
        finally:
            contained = end_ref_capture()
        total = ser.serialized_size(smeta, views)
        if total <= CONFIG.object_store_shm_threshold_bytes:
            out = bytearray(total)
            ser.write_to(memoryview(out), smeta, views)
            return ("v", bytes(out))
        # Large argument: implicit put, pass by reference. Synchronous for
        # the same reason as put(): the store's budget accounting must not
        # lag behind a writer looping over f.remote(big_array).
        oid = ObjectID.for_put(self.worker_id)
        implicit_ref = ObjectRef(oid)       # holder for _pin_contained
        self._note_provenance((oid,))
        self._pin_contained(oid, contained)
        if self.wire_data_plane:
            self._wire_put(oid, _flat_bytes(smeta, views, total), total)
            return ("r", implicit_ref.id)
        meta = self._local_store_large(oid, smeta, views, total)
        if meta is None:
            meta = self.store_large(oid, smeta, views, total)
            self._sync_put(meta)
        return ("r", implicit_ref.id)

    # ---------------------------------------------------------------- tasks
    def ensure_function(self, function_id: bytes, blob_fn) -> None:
        if function_id in self._registered_fns:
            return
        self._send(P.KV_PUT, (b"fn:" + function_id, blob_fn(), False))
        self._registered_fns.add(function_id)

    def submit_task(self, function_id: bytes, name: str, args, kwargs,
                    num_returns: int, resources: Dict[str, float],
                    max_retries: int, scheduling_strategy=None,
                    retry_exceptions: bool = False,
                    runtime_env: Optional[dict] = None) -> List[ObjectRef]:
        task_id = TaskID.for_job(self.job_id)
        packed, pkw = self.pack_args(args, kwargs)
        streaming = num_returns == -1
        return_ids = ([] if streaming
                      else [ObjectID.for_task_return(task_id, i)
                            for i in range(num_returns)])
        spec = P.TaskSpec(
            task_id=task_id, job_id=self.job_id, name=name,
            function_id=function_id, args=packed, kwargs=pkw,
            num_returns=num_returns, return_ids=return_ids,
            resources=resources,
            # no lineage reconstruction of partially-consumed streams
            # (the reference restricts retries of generators similarly)
            max_retries=0 if streaming else max_retries,
            retry_exceptions=retry_exceptions,
            scheduling_strategy=scheduling_strategy,
            owner_id=self.worker_id.binary(),
            namespace=self._active_namespace(),
            runtime_env=runtime_env,
            trace_context=self._trace_context(),
            request_ctx=_ctx.request_ctx.get())
        self._note_provenance(return_ids)
        self._send_submission(P.SUBMIT_TASK, spec)
        if streaming:
            return ObjectRefGenerator(task_id)
        return [ObjectRef(oid) for oid in return_ids]

    @staticmethod
    def _trace_context() -> Optional[dict]:
        from ..util import tracing
        return tracing.propagation_context()

    def send_profile_event(self, kind: str, payload) -> None:
        self._send(P.PROFILE_EVENT, (kind, payload))

    def create_actor(self, spec: P.ActorSpec) -> None:
        if spec.creation_return_id is not None:
            self._note_provenance((spec.creation_return_id,))
        self._send(P.CREATE_ACTOR, spec)

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args, kwargs, num_returns: int, seq_no: int,
                          name: str = "") -> List[ObjectRef]:
        task_id = TaskID.for_job(self.job_id)
        packed, pkw = self.pack_args(args, kwargs)
        streaming = num_returns == -1
        return_ids = ([] if streaming
                      else [ObjectID.for_task_return(task_id, i)
                            for i in range(num_returns)])
        spec = P.TaskSpec(
            task_id=task_id, job_id=self.job_id,
            name=name or method_name, function_id=b"",
            args=packed, kwargs=pkw, num_returns=num_returns,
            return_ids=return_ids, resources={},
            actor_id=actor_id, method_name=method_name, seq_no=seq_no,
            owner_id=self.worker_id.binary(),
            namespace=self._active_namespace(),
            trace_context=self._trace_context(),
            request_ctx=_ctx.request_ctx.get())
        self._note_provenance(return_ids)
        self._send_submission(P.SUBMIT_ACTOR_TASK, spec)
        if streaming:
            return ObjectRefGenerator(task_id)
        return [ObjectRef(oid) for oid in return_ids]

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        self._send(P.KILL_ACTOR, (actor_id, no_restart))

    def save_actor_checkpoint(self, actor_id: ActorID, seq: int,
                              blob: bytes) -> bool:
        """Persist one actor-state snapshot in the control plane.
        SYNCHRONOUS on purpose: the worker checkpoints before reporting
        the triggering call done, so a completion the caller observed
        is never ahead of the state a restart would restore. Large
        blobs ride out-of-band (zero-copy iovec)."""
        return self._request(
            P.ACTOR_CHECKPOINT,
            lambda rid: (rid, actor_id, seq, P.oob_wrap(blob))).result()

    def get_actor_checkpoint(self, actor_id: ActorID):
        """(seq, blob) of the actor's latest checkpoint, or None."""
        return self._request(
            P.ACTOR_CHECKPOINT_GET, lambda rid: (rid, actor_id)).result()

    def actor_exit(self, actor_id: ActorID, reason: str) -> None:
        """Worker-side intentional exit of its own actor (the send half
        of ``ray_tpu.exit_actor()``)."""
        self._send(P.ACTOR_EXIT, (actor_id, reason))

    def cancel_task(self, task_id: TaskID, force: bool) -> None:
        self._send(P.CANCEL_TASK, (task_id, force))

    def get_named_actor(self, name: str, namespace: str) -> Optional[dict]:
        fut = self._request(P.GET_NAMED_ACTOR,
                            lambda rid: (rid, name, namespace))
        return fut.result()

    def fetch_function(self, function_id: bytes) -> Optional[bytes]:
        fut = self._request(P.FETCH_FUNCTION, lambda rid: (rid, function_id))
        return fut.result()

    # ------------------------------------------------------------------ kv
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True) -> None:
        self._send(P.KV_PUT, (key, value, overwrite))

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self._request(P.KV_GET, lambda rid: (rid, key)).result()

    def kv_del(self, key: bytes) -> None:
        self._send(P.KV_DEL, key)

    def kv_keys(self, prefix: bytes) -> List[bytes]:
        return self._request(P.KV_KEYS, lambda rid: (rid, prefix)).result()

    # ---------------------------------------------------------------- info
    def cluster_info(self, what: str) -> Any:
        return self._request(P.CLUSTER_INFO, lambda rid: (rid, what)).result()

    def state_query(self, what: str, filters=None) -> Any:
        return self._request(P.STATE_QUERY,
                             lambda rid: (rid, what, filters)).result()

    def cluster_stacks(self, timeout_s: float = 5.0) -> Any:
        """Thread dumps of every node/worker/driver process, aggregated
        and deduplicated by the control plane (reference: `ray stack`)."""
        return self._request(
            P.CLUSTER_STACKS,
            lambda rid: (rid, timeout_s)).result(timeout=timeout_s + 30.0)

    def cluster_profile(self, opts: dict) -> Any:
        """Cluster-wide sampling profile; blocks for the duration."""
        duration = float(opts.get("duration_s", 5.0))
        return self._request(
            P.CLUSTER_PROFILE,
            lambda rid: (rid, dict(opts))).result(timeout=duration + 60.0)

    def collective_health(self, timeout_s: float = 2.0) -> Any:
        """Cluster-wide collective hang diagnosis: every rank's flight-
        recorder watermarks, diffed into verdicts (dead rank / lost
        chunk / lagging rank). Workers call this too — a rank that just
        timed out diagnoses the hang before surfacing it."""
        return self._request(
            P.CLUSTER_COLL,
            lambda rid: (rid, "health", timeout_s)).result(
                timeout=timeout_s + 30.0)

    def flight_records(self, timeout_s: float = 2.0) -> Any:
        """Every process's recent flight-recorder events + completed-op
        records (the raw material behind ``state.flight_records()`` and
        the timeline's collective spans)."""
        return self._request(
            P.CLUSTER_COLL,
            lambda rid: (rid, "records", timeout_s)).result(
                timeout=timeout_s + 30.0)

    def create_placement_group(self, spec: P.PlacementGroupSpec):
        return self._request(P.CREATE_PG, lambda rid: (rid, spec)).result()

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        self._send(P.REMOVE_PG, pg_id)


def function_id_of(blob: bytes) -> bytes:
    return hashlib.sha1(blob).digest()
