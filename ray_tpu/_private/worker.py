"""Worker process entrypoint: executes tasks and hosts actor instances.

Equivalent role to the reference's ``default_worker.py`` +
``CoreWorker::RunTaskExecutionLoop`` (``python/ray/_private/workers/
default_worker.py``, ``_raylet.pyx:3035`` run_task_loop,
``task_execution_handler`` ``_raylet.pyx:1972``): registers with the node
service, pulls pushed tasks off its socket, loads functions from the
control-plane KV (cached by content hash), executes, and seals returns
either inline or into shared memory. Nested API calls (a task calling
``remote``/``get``) reuse the same connection through the process-global
``CoreClient``.
"""

from __future__ import annotations

import asyncio
import ctypes
import inspect
import itertools
import os
import signal
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from queue import Empty, SimpleQueue
from typing import Any, Dict, List, Optional

from .. import exceptions
from . import context
from . import failpoints
from . import fieldsan
from . import protocol as P
from . import telemetry
from .client import CoreClient
from .config import CONFIG
from .ids import JobID, NodeID, ObjectID, WorkerID
from .object_store import ObjectMeta, create_segment
from . import serialization as ser

M_ACTOR_CKPTS = telemetry.define(
    "counter", "rtpu_actor_checkpoints_total",
    "Actor state snapshots captured by this worker (periodic per "
    "actor_checkpoint_interval_calls, or on demand via "
    "ray_tpu.actor_checkpoint()) and persisted in the control plane")
M_ACTOR_RESTORES = telemetry.define(
    "counter", "rtpu_actor_restores_total",
    "Restarted actors whose state was replayed from their latest "
    "checkpoint (restore_checkpoint ran before any queued call) "
    "instead of starting empty from __init__")


@fieldsan.guarded
class WorkerRuntime:
    def __init__(self, socket_path: str, node_id: NodeID,
                 worker_id: WorkerID):
        self.node_id = node_id
        self.worker_id = worker_id
        self.conn = P.connect_unix(socket_path)
        self.client = CoreClient(self.conn, JobID.nil(), worker_id,
                                 P.KIND_WORKER)
        self.client.node_id = node_id
        context.current_client = self.client
        context.in_worker = True
        self._functions: Dict[bytes, Any] = {}
        self._actor_instance: Any = None
        self._actor_spec: Optional[P.ActorSpec] = None
        self._exec_queue: "SimpleQueue" = SimpleQueue()
        self._cancelled_queued: set = set()
        # True while the exec thread sits in a blocking get(); the
        # reader bounces task leases that arrive in that window (the
        # exec-thread drain at block entry can't see them)
        self._blocked_in_get = False
        self.client.on_worker_block = self._return_leased_tasks
        self.client.on_worker_unblock = self._on_unblock
        # named so `rtpu stack` dumps and profiles identify task code at
        # a glance (and the profiler's runtime-thread filter keeps it)
        self._exec_thread = threading.Thread(target=self._exec_loop,
                                             name="task-exec", daemon=True)
        # TASK_DONE coalescing: a DONE sent while MORE tasks are queued
        # is enqueued lazily (no inline drain) so back-to-back tiny-task
        # completions pack into one frame — the symmetric half of the
        # node's EXECUTE_BATCH. The kicker thread bounds withholding to
        # ~1-2ms: a slow successor task can never sit on a predecessor's
        # result (any direct send on the conn also flushes it earlier).
        self._kick_ev = threading.Event()
        self._kicker: Optional[threading.Thread] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._aio_loop: Optional[asyncio.AbstractEventLoop] = None
        self._current_task_thread: Optional[int] = None
        # checkpointable-actor bookkeeping: atomic snapshot-sequence
        # allocator (itertools.count — concurrent on-demand checkpoints
        # from a threaded actor get distinct seqs without a lock;
        # re-seeded past the restored checkpoint so a restart never
        # allocates behind the plane) and completed calls since the
        # last capture (the periodic trigger)
        self._ckpt_counter = itertools.count(1)
        self._ckpt_calls = 0
        self._ckpt_last_t = time.monotonic()

    # ------------------------------------------------------------ main loop
    def run(self) -> None:
        signal.signal(signal.SIGINT, self._on_sigint)
        self.conn.send((P.REGISTER, (P.KIND_WORKER,
                                     self.worker_id.binary(), os.getpid())))
        self._exec_thread.start()
        while True:
            # burst receive: leases the node's writer coalesced enqueue
            # in one wakeup (the exec thread drains them back-to-back)
            msgs = self.conn.recv_many()
            if msgs is None:
                os._exit(0)
            for op, payload in msgs:
                if op == P.EXECUTE_TASK:
                    if not self._maybe_bounce(payload):
                        self._enqueue_execute(payload)
                elif op == P.EXECUTE_BATCH:
                    # the batch frame amortizes the node->worker side;
                    # each task's DONE still leaves per task (withholding
                    # an early result until a batch's last task finished
                    # would stall callers behind a slow successor) —
                    # transport write-coalescing batches the frames
                    for item in payload:
                        if not self._maybe_bounce(item):
                            self._enqueue_execute(item)
                elif op == P.CANCEL_QUEUED:
                    self._cancelled_queued.add(payload)
                elif op == P.SHUTDOWN:
                    # drain queued outbound frames (a TASK_DONE may still
                    # sit in the writer queue) before dying
                    self.conn.close()
                    os._exit(0)
                else:
                    self.client.handle_message(op, payload)

    def _maybe_bounce(self, payload) -> bool:
        """Reader-side: a plain-task lease arriving while the exec
        thread is blocked in get() would park until it unblocks; hand
        it straight back instead (it never enters the queue, so it can
        never also run here). The bounce echoes the grant's lease seq
        so the node can match it to the exact grant (a bounce landing
        after the grant was superseded is dropped as stale)."""
        if not self._blocked_in_get or payload[0] != "task" \
                or self._actor_spec is not None:
            return False
        self.conn.send((P.RETURN_LEASED, [(payload[1].task_id, payload[4])]))
        return True

    def _on_unblock(self) -> None:
        self._blocked_in_get = False

    def _return_leased_tasks(self) -> None:
        """Called on the exec thread as its current task enters a
        blocking get(): drain our own queue of unstarted plain tasks
        and hand them back to the node (they may be the children this
        get() waits on — leaving them parked behind us deadlocks
        nested submission). We are the queue's only consumer, so a
        drained task can never also run here: requeueing is
        double-execution-free."""
        if self._actor_instance is not None or self._actor_spec is not None:
            return          # actor queues hold ordered actor calls
        self._blocked_in_get = True
        returned: List = []
        while True:
            try:
                item = self._exec_queue.get_nowait()
            except Empty:
                break
            if item[0] == "task":
                returned.append((item[1].task_id, item[4]))
            else:           # not leaseable work; keep it queued
                self._exec_queue.put(item)
                break
        if returned:
            self.conn.send((P.RETURN_LEASED, returned))

    def _enqueue_execute(self, payload) -> None:
        kind, spec, deps = payload[0], payload[1], payload[2]
        if kind == "actor_call" and spec.request_ctx is not None:
            # arrival stamp for the request's skew-free local queue
            # wait (in-process attribute — never serialized)
            spec._rtpu_recv_t = time.monotonic()
        if kind == "actor_call" and (
                self._pool is not None or self._aio_loop is not None):
            self._dispatch_concurrent(spec, deps)
        else:
            self._exec_queue.put(payload)

    def _on_sigint(self, signum, frame) -> None:
        """Cancellation: raise TaskCancelledError inside the task thread
        (reference analogue: KeyboardInterrupt injection on CancelTask)."""
        tid = self._current_task_thread
        if tid is not None:
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid),
                ctypes.py_object(exceptions.TaskCancelledError))

    def _exec_loop(self) -> None:
        try:
            self._exec_loop_inner()
        except BaseException:
            # a dying exec thread must not leave a zombie worker (reader
            # alive, nothing executing): surface and exit so the node
            # reaps the process and retries its tasks
            traceback.print_exc(file=sys.stderr)
            os._exit(1)

    def _exec_loop_inner(self) -> None:
        while True:
            kind, spec, deps, actor_spec, _seq = self._exec_queue.get()
            if spec.task_id in self._cancelled_queued:
                # skipped, not executed: report NO return metas — for a
                # rescued lease the task re-runs elsewhere and owns
                # these return ids; for a user cancel the node already
                # failed the returns itself
                self._cancelled_queued.discard(spec.task_id)
                self.conn.send((P.TASK_DONE,
                                (spec.task_id, [], None, kind, None)))
                continue
            self._current_task_thread = threading.get_ident()
            try:
                self._run_one(kind, spec, deps, actor_spec)
            finally:
                self._current_task_thread = None

    def _ensure_kicker(self) -> None:
        if self._kicker is None:
            t = threading.Thread(target=self._kick_loop,
                                 name="done-kicker", daemon=True)
            self._kicker = t
            t.start()
        self._kick_ev.set()

    def _kick_loop(self) -> None:
        """Flush lazily-queued TASK_DONE frames ~1ms after the first one
        was held — the upper bound on how long a completed task's result
        can wait for batchmates."""
        while True:
            self._kick_ev.wait()
            self._kick_ev.clear()
            time.sleep(0.001)
            try:
                self.conn.kick()
            except OSError:
                return

    def _dispatch_concurrent(self, spec: P.TaskSpec, deps) -> None:
        if self._aio_loop is not None:
            asyncio.run_coroutine_threadsafe(
                self._run_async(spec, deps), self._aio_loop)
        else:
            self._pool.submit(self._run_one, "actor_call", spec, deps, None)

    # ------------------------------------------------------------ execution
    def _run_one(self, kind: str, spec: P.TaskSpec, deps,
                 actor_spec: Optional[P.ActorSpec]) -> None:
        context.current_task_id = spec.task_id
        context.current_task_name = spec.name
        context.current_accel_ids = spec.accel_ids
        # inherit the submitting job's namespace so nested named-actor
        # lookups/creations resolve where the driver's would (ContextVar:
        # concurrent calls on a threaded actor don't race each other)
        context.current_namespace.set(
            actor_spec.namespace if actor_spec else spec.namespace)
        # request-scoped baggage: re-bound for the call's duration so
        # the request's nested submissions carry it onward and a serve
        # replica reads its request context without paying an arg slot
        req_token = context.request_ctx.set(spec.request_ctx)
        recv_token = (context.request_recv_t.set(
            getattr(spec, "_rtpu_recv_t", None))
            if spec.request_ctx is not None else None)
        span_cm = self._task_span(kind, spec)
        try:
            with span_cm:
                if kind == "task":
                    fn = self._get_function(spec.function_id)
                    args, kwargs = self._load_args(spec, deps)
                    failpoints.fp("worker.task.begin", name=spec.name)
                    result = fn(*args, **kwargs)
                elif kind == "actor_create":
                    result = self._create_actor(actor_spec, spec, deps)
                else:  # actor_call
                    args, kwargs = self._load_args(spec, deps)
                    failpoints.fp("actor.call.begin",
                                  method=spec.method_name, name=spec.name)
                    method = getattr(self._actor_instance, spec.method_name)
                    result = method(*args, **kwargs)
                    if inspect.iscoroutine(result):
                        # sync actor defining an async method: run it here
                        result = asyncio.new_event_loop(
                        ).run_until_complete(result)
            if kind == "actor_call":
                # BEFORE the result is reported: a completion the
                # caller observed is never newer than the checkpoint a
                # restart would restore (a capture failure fails the
                # call — resuming silently behind would break that)
                self._maybe_checkpoint()
            self._send_done(spec, kind, result, None)
        except BaseException as e:  # noqa: BLE001
            self._send_done(spec, kind, None, e)
        finally:
            context.request_ctx.reset(req_token)
            if recv_token is not None:
                context.request_recv_t.reset(recv_token)
            context.current_task_id = None
            context.current_task_name = None
            context.current_accel_ids = None   # slot may be recycled next
            # don't leak this task's trace into spans a later codepath
            # might open on the same pool thread
            from ..util import tracing
            tracing.set_remote_parent(None)

    @staticmethod
    def _task_span(kind: str, spec: P.TaskSpec):
        """Span around execution, parented to the submitter's context
        carried in the spec (no-op context manager when neither this
        process nor the submitter is tracing). A non-None trace_context
        — even an empty one — means the SUBMITTER had tracing on, which
        overrides this node's own config (remote nodes never see the
        driver's _system_config)."""
        from ..util import tracing
        if not (tracing.enabled() or spec.trace_context is not None):
            import contextlib
            return contextlib.nullcontext()
        tracing.set_remote_parent(spec.trace_context or None)
        # literal prefixes (not f"{kind}::"): the span-name registry lint
        # (scripts/check_metrics.py) extracts them statically
        return tracing.start_span(
            ("task::" if kind == "task" else
             "actor_create::" if kind == "actor_create" else
             "actor_call::") + spec.name,
            attributes={"task_id": spec.task_id.hex()}, force=True)

    async def _run_async(self, spec: P.TaskSpec, deps) -> None:
        context.current_namespace.set(spec.namespace)
        req_token = context.request_ctx.set(spec.request_ctx)
        # actor-wide slots: identical for every call of this actor, so
        # the module-global is safe under asyncio interleaving
        context.current_accel_ids = spec.accel_ids
        context.current_task_name = spec.name   # best-effort (interleaved)
        # stackless span: concurrent async calls interleave on one loop
        # thread, so the thread-local span stack would mis-nest them
        from ..util import tracing
        span = None
        if tracing.enabled() or spec.trace_context is not None:
            span = tracing.begin_span(
                "actor_call::" + spec.name, spec.trace_context or None,
                attributes={"task_id": spec.task_id.hex()})
        try:
            args, kwargs = self._load_args(spec, deps)
            method = getattr(self._actor_instance, spec.method_name)
            result = method(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            tracing.end_span(span)
            self._send_done(spec, "actor_call", result, None)
        except BaseException as e:  # noqa: BLE001
            tracing.end_span(span, error=type(e).__name__)
            self._send_done(spec, "actor_call", None, e)
        finally:
            context.request_ctx.reset(req_token)
            # best-effort under interleaving (another call's name may be
            # re-set right after) — but a stale name on an IDLE worker
            # would misattribute every filtered profile sample forever
            context.current_task_name = None

    def _create_actor(self, actor_spec: P.ActorSpec, spec: P.TaskSpec,
                      deps) -> Any:
        cls = ser.loads_function(actor_spec.class_blob)
        args, kwargs = self._load_args(spec, deps)
        self._actor_spec = actor_spec
        context.current_actor_id = actor_spec.actor_id
        if actor_spec.is_async:
            self._aio_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._aio_loop.run_forever,
                                 daemon=True)
            t.start()
        elif actor_spec.max_concurrency > 1:
            self._pool = ThreadPoolExecutor(
                max_workers=actor_spec.max_concurrency)
        self._actor_instance = cls(*args, **kwargs)
        label = getattr(self._actor_instance, "__rtpu_log_label__", None)
        if label:
            # this process's log lines get a human name in the driver's
            # "(worker ...)" prefix (serve replicas set their
            # deployment#tag, so `rtpu logs` greps by deployment)
            self.conn.send((P.SET_LOG_LABEL, str(label)[:64]))
        self._restore_checkpoint(actor_spec)
        context.actor_checkpoint_hook = self.checkpoint_now
        return None

    # ------------------------------------------ checkpointable actors
    # Opt-in protocol: a class defining ``save_checkpoint(self) ->
    # state`` (and, to resume, ``restore_checkpoint(self, state)``) is
    # checkpointable. Capture is periodic (every
    # ``actor_checkpoint_interval_calls`` completed calls) or on demand
    # (``ray_tpu.actor_checkpoint()`` inside a method); the blob lives
    # in the control plane keyed by actor id, so the SAME id restored
    # after a worker- or node-level restart finds it. Restore runs
    # inside the (re-)creation task — strictly before any queued call
    # drains, so a restarted rank resumes at its last checkpointed
    # step, not from __init__.

    def _restore_checkpoint(self, actor_spec: P.ActorSpec) -> None:
        inst = self._actor_instance
        if not (hasattr(inst, "restore_checkpoint")
                or hasattr(inst, "save_checkpoint")):
            return
        ckpt = self.client.get_actor_checkpoint(actor_spec.actor_id)
        if ckpt is None:
            return                      # first creation: nothing saved
        seq, blob = ckpt
        # resume the sequence even for save-only classes: a restarted
        # incarnation restarting at seq 1 would have every later save
        # rejected by the plane's monotonic guard
        self._ckpt_counter = itertools.count(int(seq) + 1)
        if hasattr(inst, "restore_checkpoint"):
            inst.restore_checkpoint(ser.from_bytes(bytes(blob)))
            telemetry.counter_inc(M_ACTOR_RESTORES)

    def _maybe_checkpoint(self) -> None:
        inst = self._actor_instance
        if inst is None or not hasattr(inst, "save_checkpoint"):
            return
        if self._pool is not None or self._aio_loop is not None:
            # concurrent actors (max_concurrency>1 / async) have no
            # quiescent point between calls: an automatic snapshot here
            # could serialize state another call is mid-mutating (and
            # the async path never reaches this method at all) — such
            # actors checkpoint on demand at points THEY know are safe
            return
        every = CONFIG.actor_checkpoint_interval_calls
        every_s = CONFIG.actor_checkpoint_interval_s
        self._ckpt_calls += 1
        # TIME trigger beside the call-count one, checked at the same
        # quiescent point (a call just completed — for sync actors the
        # only moment a snapshot is guaranteed consistent; an IDLE actor
        # mutates no state, so there is nothing new to capture between
        # calls): a slow-call actor whose calls each outlast the
        # interval checkpoints once per call even when the call-count
        # trigger would never fire
        if (every > 0 and self._ckpt_calls >= every) or \
                (every_s > 0
                 and time.monotonic() - self._ckpt_last_t >= every_s):
            self.checkpoint_now()

    def checkpoint_now(self) -> int:
        """Capture + persist the actor's state; returns the durable
        snapshot's sequence number (the ray_tpu.actor_checkpoint()
        hook). A threaded actor may call this concurrently without
        breaking anything mechanical (seqs are allocated atomically,
        BEFORE the capture, and a rejected save never overwrites a
        newer one) — but the ORDER of two overlapping captures is
        inherently ambiguous: each call guarantees only that a
        snapshot at least as new as its own is durable. An actor that
        needs strict capture ordering must serialize its own
        checkpoint points (which 'checkpoint at points YOU know are
        safe' already implies)."""
        inst = self._actor_instance
        if inst is None or self._actor_spec is None:
            raise RuntimeError("no actor instance in this worker")
        if not hasattr(inst, "save_checkpoint"):
            raise RuntimeError(
                f"actor {type(inst).__name__} defines no "
                "save_checkpoint() — the checkpoint protocol is opt-in")
        aid = self._actor_spec.actor_id
        # seq BEFORE capture: allocation order then matches capture
        # START order, so a capture that began later (and may contain
        # later mutations) can never persist under a LOWER seq
        seq = next(self._ckpt_counter)
        blob = ser.to_bytes(inst.save_checkpoint())
        if not self.client.save_actor_checkpoint(aid, seq, blob):
            cur = self.client.get_actor_checkpoint(aid)
            seq = int(cur[0]) if cur is not None else 0
            # re-seed so the NEXT capture strictly supersedes whatever
            # is there (benign if a concurrent caller re-seeds too)
            self._ckpt_counter = itertools.count(seq + 1)
        self._ckpt_calls = 0
        self._ckpt_last_t = time.monotonic()
        telemetry.counter_inc(M_ACTOR_CKPTS)
        return seq

    def _get_function(self, function_id: bytes):
        fn = self._functions.get(function_id)
        if fn is None:
            blob = self.client.fetch_function(function_id)
            if blob is None:
                raise RuntimeError(
                    f"function {function_id.hex()[:12]} not found in KV")
            fn = ser.loads_function(blob)
            self._functions[function_id] = fn
        return fn

    def _load_args(self, spec: P.TaskSpec, deps: Dict[ObjectID, ObjectMeta]):
        args = [self._load_one(slot, deps) for slot in spec.args]
        kwargs = {k: self._load_one(slot, deps)
                  for k, slot in spec.kwargs.items()}
        return args, kwargs

    def _load_one(self, slot, deps):
        tag, val = slot
        if tag == "v":
            return ser.from_bytes(val)
        meta = deps.get(val)
        if meta is None:
            # dependency not pre-resolved (nested ref): fetch via client
            from .object_ref import ObjectRef
            return self.client.get([ObjectRef(val)])[0]
        return self.client.reader.load(meta)

    # -------------------------------------------------------------- returns
    def _send_done(self, spec: P.TaskSpec, kind: str, result: Any,
                   exc: Optional[BaseException]) -> None:
        if spec.num_returns == -1 and exc is None:
            self._stream_returns(spec, kind, result)
            return
        metas: List[ObjectMeta] = []
        err_bytes: Optional[bytes] = None
        if exc is not None:
            if isinstance(exc, (exceptions.TaskCancelledError,
                                exceptions.RayTpuError)):
                wrapped: BaseException = exc
            else:
                wrapped = exceptions.TaskError(
                    type(exc).__name__, str(exc),
                    "".join(traceback.format_exception(
                        type(exc), exc, exc.__traceback__)),
                    task_name=spec.name)
            err_bytes = ser.to_bytes(wrapped)
            for oid in spec.return_ids:
                metas.append(ObjectMeta(object_id=oid, size=len(err_bytes),
                                        error=err_bytes))
        else:
            values: List[Any]
            if spec.num_returns == 1:
                values = [result]
            elif spec.num_returns == 0:
                values = []
            else:
                values = list(result)
                if len(values) != spec.num_returns:
                    self._send_done(spec, kind, None, ValueError(
                        f"task {spec.name} declared num_returns="
                        f"{spec.num_returns} but returned {len(values)}"))
                    return
            for oid, value in zip(spec.return_ids, values):
                metas.append(self._store_return(oid, value))
        # borrows registered during execution must land BEFORE the
        # node unpins this task's args (same conn => ordered frames);
        # buffered nested submissions likewise precede our DONE
        self.client.flush_submissions()
        self.client.flush_refs()
        # a STREAMING task that failed before iteration started (arg
        # load, actor method raising before returning a generator) must
        # still end its stream — gen_count=0 + the error — or consumers
        # parked on item 0 hang forever
        gen_count = 0 if spec.num_returns == -1 else None
        done = (P.TASK_DONE,
                (spec.task_id, metas, err_bytes, kind, gen_count))
        if kind != "actor_create" and not self._exec_queue.empty():
            # more work is already queued: coalesce this DONE with the
            # next completions (kicker bounds the hold to ~1-2ms)
            self.conn.send_lazy(done)
            self._ensure_kicker()
        else:
            self.conn.send(done)
        # unconditional: force-traced spans exist even when THIS node's
        # config has tracing off (flush is a no-op on an empty buffer)
        from ..util import tracing
        tracing.flush()
        # telemetry deltas recorded during the task (collective ops,
        # serve replicas, data blocks, user metrics) ship at task
        # boundaries — rate-limited so a storm of tiny recording tasks
        # pays at most ~5 control-plane frames/s, not one per task; the
        # background flusher covers the tail
        from . import telemetry
        telemetry.maybe_flush()

    def _stream_returns(self, spec: P.TaskSpec, kind: str,
                        result: Any) -> None:
        """Drive a streaming (num_returns=\"streaming\") task: store and
        report each yielded item as it is produced, pacing against the
        consumer with a bounded in-flight window (reference:
        ReportGeneratorItemReturns, ``core_worker.proto:396``)."""
        window = CONFIG.generator_backpressure_window
        produced = 0
        self.client.gen_credit_init(spec.task_id)
        err: Optional[BaseException] = None
        try:
            it = iter(result)
        except TypeError:
            err = exceptions.TaskError(
                "TypeError",
                f"streaming task {spec.name} must return an iterable/"
                f"generator, got {type(result).__name__}", "",
                task_name=spec.name)
            it = iter(())
        while err is None:
            try:
                item = next(it)
            except StopIteration:
                break
            except BaseException as e:  # noqa: BLE001 — reported to owner
                err = e if isinstance(e, exceptions.RayTpuError) else \
                    exceptions.TaskError(
                        type(e).__name__, str(e),
                        "".join(traceback.format_exception(
                            type(e), e, e.__traceback__)),
                        task_name=spec.name)
                break
            oid = ObjectID.for_gen_item(spec.task_id, produced)
            meta = self._store_return(oid, item)
            self.conn.send((P.GEN_ITEM, (spec.task_id, produced, meta)))
            produced += 1
            self.client.gen_wait_credit(spec.task_id, produced, window)
        self.client.gen_credit_drop(spec.task_id)
        err_bytes = ser.to_bytes(err) if err is not None else None
        self.client.flush_submissions()
        self.client.flush_refs()
        self.conn.send((P.TASK_DONE,
                        (spec.task_id, [], err_bytes, kind, produced)))
        from ..util import tracing
        tracing.flush()
        from . import telemetry
        telemetry.maybe_flush()

    def _store_return(self, oid: ObjectID, value: Any) -> ObjectMeta:
        from .object_ref import begin_ref_capture, end_ref_capture
        begin_ref_capture()
        try:
            smeta, views = ser.serialize(value)
        finally:
            contained = end_ref_capture()
        if contained:
            # refs living only inside this return would lose their last
            # holder when our locals die; the node pins them until the
            # return object itself is freed. Sent BEFORE this return's
            # TASK_DONE/GEN_ITEM (same conn => ordered).
            self.conn.send((P.RETURN_REFS, (oid, contained)))
        total = ser.serialized_size(smeta, views)
        if total <= CONFIG.object_store_shm_threshold_bytes:
            out = bytearray(total)
            ser.write_to(memoryview(out), smeta, views)
            return ObjectMeta(object_id=oid, size=total, inline=bytes(out))
        # arena Create/Seal through the local node store when available
        return self.client.store_large(oid, smeta, views, total)


def main() -> None:
    socket_path, node_hex, worker_hex = sys.argv[1], sys.argv[2], sys.argv[3]
    rt = WorkerRuntime(socket_path, NodeID.from_hex(node_hex),
                       WorkerID.from_hex(worker_hex))
    rt.run()


if __name__ == "__main__":
    main()
