"""Compiled-in configuration table, overridable via environment variables.

Equivalent role to the reference's ``RAY_CONFIG`` table
(``src/ray/common/ray_config_def.h``, 209 tunables overridable via ``RAY_*``
env vars or a system-config JSON). Here every entry is a typed default that
can be overridden by ``RTPU_<NAME>`` in the environment or by passing
``_system_config={...}`` to ``init()``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

_ENV_PREFIX = "RTPU_"

# name -> (type, default, help)
_CONFIG_DEFS: Dict[str, tuple] = {
    # --- object store ---
    "object_store_memory_mb": (int, 2048, "shm budget for the local object store"),
    "object_store_shm_max_bytes": (int, 0,
                                   "byte-denominated override of the store/arena "
                                   "budget; 0 = object_store_memory_mb << 20"),
    "object_store_shm_threshold_bytes": (int, 100 * 1024,
                                         "values <= this stay on the in-heap inline "
                                         "path (carried in RPC frames); larger values "
                                         "land in the shm arena / a segment "
                                         "(reference: task_rpc_inlined_bytes_limit)"),
    "object_store_spill_threshold": (float, 0.8,
                                     "fraction of store memory above which coldest "
                                     "unpinned primary copies are spilled to disk"),
    "object_store_spill_dir": (str, "",
                               "directory for spilled objects (default: session dir)"),
    "object_store_lazy_put": (bool, True,
                              "head-driver puts of large values defer the shm copy "
                              "until first cross-process demand or spill pressure "
                              "(zero-copy put; the serialized views alias the "
                              "caller's buffers until promotion, so a put value "
                              "must not be mutated afterwards — same immutability "
                              "contract the reference's plasma copies enforce)"),
    "use_native_arena": (bool, True,
                         "allocate store objects from the C++ shm arena "
                         "(native/object_arena.cpp) when the library builds; "
                         "falls back to per-object segments"),
    # --- scheduler ---
    "worker_pipeline_depth": (int, 4,
                              "max tasks leased to one busy worker (running "
                              "+ queued) when more same-shape tasks are "
                              "pending than idle workers; grants/returns "
                              "carry per-worker monotonic lease seqs so "
                              "stale rescues are dropped (reference: "
                              "worker-lease reuse, direct_task_transport.h)."
                              " 1 disables pipelining"),
    "dispatcher_event_batch": (int, 128,
                               "max queued events the node dispatcher "
                               "drains per loop turn; the batch is handled "
                               "with one scheduling pass and one outbox "
                               "flush (a burst of TASK_DONEs frees N "
                               "workers, then dispatches once)"),
    "submit_batch_max_specs": (int, 200,
                               "client-side combining buffer: task/actor-"
                               "call submissions coalesce into one "
                               "SUBMIT_BATCH frame, flushed at this count "
                               "or by the next blocking op / flusher "
                               "cadence"),
    "scheduler_spread_threshold": (float, 0.5,
                                   "hybrid policy: pack below this node utilization, "
                                   "spread above (reference: scheduler_spread_threshold)"),
    "scheduler_top_k_fraction": (float, 0.2,
                                 "hybrid policy: random choice among best k nodes"),
    "scheduler_route_debit_ttl_s": (float, 2.0,
                                    "how long a routed-but-unconfirmed task's "
                                    "resources stay debited from the router's "
                                    "view of the target node (bridges heartbeat "
                                    "staleness so bursts don't pile onto one node)"),
    "ref_zero_grace_ms": (int, 50,
                          "delay between an object's refcount reaching zero "
                          "and its free, absorbing in-flight borrower "
                          "registrations (a ref passed through a queue actor "
                          "briefly reads as zero between the sender's drop "
                          "and the receiver's register)"),
    "generator_backpressure_window": (int, 16,
                                      "max unconsumed streaming-generator items "
                                      "in flight before the producer blocks "
                                      "(0 = unbounded; reference: "
                                      "_generator_backpressure_num_objects)"),
    "scheduler_spillback_delay_s": (float, 0.25,
                                    "re-route a queued task to another node with "
                                    "free capacity after it has starved locally "
                                    "this long (reference: lease spillback, "
                                    "cluster_task_manager.cc)"),
    "worker_lease_timeout_s": (float, 30.0, "lease request timeout"),
    # --- worker pool ---
    "num_prestart_workers": (int, 0, "workers to pre-start at node boot (0 = num_cpus)"),
    "idle_worker_killing_time_s": (float, 300.0, "kill idle workers after this long"),
    "worker_register_timeout_s": (float, 30.0, "worker registration handshake timeout"),
    "maximum_startup_concurrency": (int, 16, "max concurrent worker process launches"),
    "runtime_env_setup_timeout_s": (float, 600.0,
                                    "extra registration budget for workers "
                                    "building a pip env before first start "
                                    "(reference: "
                                    "runtime_env_setup_timeout_seconds)"),
    "worker_startup_max_failures": (int, 3,
                                    "consecutive startup failures per runtime env "
                                    "before pending tasks fail with "
                                    "RuntimeEnvSetupError (reference: PopWorker "
                                    "failure callback)"),
    "arena_free_quarantine_s": (float, 30.0,
                                "freed arena blocks whose object was ever read "
                                "are quarantined this long before reuse "
                                "(readers may hold zero-copy views)"),
    # --- autoscaling ---
    "infeasible_task_grace_s": (float, 0.0,
                                "park tasks/actors with no feasible node this "
                                "long (autoscaler scale-up window) instead of "
                                "failing immediately; 0 = fail fast"),
    # --- memory monitor / OOM killing ---
    "memory_monitor_refresh_ms": (int, 1000,
                                  "system-memory poll period; 0 disables the "
                                  "monitor (reference: memory_monitor.h:52)"),
    "memory_usage_threshold": (float, 0.95,
                               "used-memory fraction above which a worker is "
                               "killed (reference: "
                               "RAY_memory_usage_threshold)"),
    "task_oom_retries_default": (int, 3,
                                 "retries for tasks killed by the memory "
                                 "monitor, counted separately from "
                                 "max_retries (reference: task_oom_retries)"),
    # --- object ownership & memory introspection ---
    "object_callsite_enabled": (bool, True,
                                "record a creation callsite (file:line + "
                                "task/actor name) per put()/.remote() "
                                "return and ship it with ref "
                                "registration; powers state.memory_"
                                "summary(), `rtpu memory` attribution "
                                "and the OOM autopsy (reference: "
                                "RAY_record_ref_creation_sites). Off = "
                                "the submission hot path is exactly the "
                                "pre-provenance code"),
    "memory_leak_sweep_interval_s": (float, 10.0,
                                     "control-plane object-leak sweep "
                                     "period: flags objects whose only "
                                     "ref holders live on dead nodes, or "
                                     "that sat pinned with zero holders "
                                     "past the TTL; 0 disables"),
    "memory_leak_pinned_ttl_s": (float, 120.0,
                                 "an object with zero ref holders that "
                                 "stays pinned (task arg / contained "
                                 "pin) longer than this is flagged as a "
                                 "suspected leak by the sweep"),
    # --- health / failure ---
    "heartbeat_period_ms": (int, 1000,
                            "resource-view sync cadence: liveness pings "
                            "every period, the availability payload only "
                            "when it changed (versioned delta sync, "
                            "reference: ray_syncer.h:86)"),
    "health_check_period_ms": (int, 3000,
                               "control-plane liveness ping period "
                               "(reference: ray_config_def.h:815)"),
    "health_check_failure_threshold": (int, 5,
                                       "consecutive missed pings before a node is dead"),
    "task_max_retries_default": (int, 3, "default retries for retriable tasks"),
    "actor_max_restarts_default": (int, 0, "default actor restarts"),
    # --- task events / observability ---
    "task_events_buffer_size": (int, 10000, "ring buffer of task state events"),
    "cluster_events_buffer_size": (int, 5000,
                                   "ring buffer of structured cluster "
                                   "events (reference: event framework, "
                                   "src/ray/util/event.h)"),
    "fieldsan": (bool, False,
                 "guarded-by field sanitizer (fieldsan.py): instrument "
                 "declared shared fields (locksan.FIELDS) and report "
                 "cross-thread accesses whose write side does not hold "
                 "the declared guard. Read once at import (descriptors "
                 "install at class creation) — set in the environment, "
                 "not _system_config; tier-1 conftest sets it"),
    "fieldsan_mode": (str, "log",
                      "fieldsan violation handling: 'log' records + "
                      "prints with both stacks; 'raise' refuses the "
                      "access with FieldRaceViolation before a write "
                      "applies"),
    "fieldsan_sample": (int, 16,
                        "capture a stack on 1-in-N guard-held accesses "
                        "(unguarded accesses always capture); higher = "
                        "cheaper instrumented path, sparser 'other "
                        "side' stacks in reports"),
    "tracing_enabled": (bool, False,
                        "record spans around task submission/execution "
                        "with cross-process context propagation "
                        "(reference: ray.util.tracing)"),
    "span_buffer_size": (int, 20000, "ring buffer of finished spans"),
    "metrics_report_interval_ms": (int, 1000,
                                   "telemetry delta-flush period (the "
                                   "background flusher; task completions "
                                   "flush rate-limited, exports flush "
                                   "synchronously)"),
    "telemetry_enabled": (bool, True,
                          "record runtime metrics (in-process shards + "
                          "batched delta push; reference: the per-node "
                          "MetricsAgent pipeline). Off = every record "
                          "call returns immediately"),
    "telemetry_sample_interval_ms": (int, 2000,
                                     "per-node host/device sampler period "
                                     "(RSS, store fill, HBM via "
                                     "device.memory_stats())"),
    "metric_series_limit": (int, 10000,
                            "max distinct (name, tags) series the control "
                            "plane keeps; excess series are dropped and "
                            "counted"),
    # --- metrics history & post-mortem bundles ---
    "metrics_history_capacity": (int, 120,
                                 "snapshot slots of the FINEST metrics-"
                                 "history ring on the control plane "
                                 "(coarser levels scale off it: level i "
                                 "keeps capacity*(2+i)/2 slots, so the "
                                 "default 120 yields 120/180/240); 0 "
                                 "disables the whole history plane — "
                                 "no periodic snapshots, no "
                                 "metrics_history queries, no doctor "
                                 "trends"),
    "metrics_history_steps": (str, "1,10,60",
                              "comma-separated seconds-per-snapshot of "
                              "each history resolution level, finest "
                              "first (multi-resolution ring: recent "
                              "history is fine-grained, older history "
                              "coarsens instead of vanishing)"),
    "metrics_history_max_bytes": (int, 8 << 20,
                                  "hard byte cap on the whole metrics-"
                                  "history ring (estimated); oldest "
                                  "finest-level frames evict first when "
                                  "over budget, so retention degrades "
                                  "gracefully under series churn"),
    "debug_bundle_on_failure": (bool, True,
                                "auto-capture a post-mortem debug "
                                "bundle (rtpu debug-bundle) on terminal "
                                "failures: collective reform budget "
                                "exhaustion, memory-monitor OOM kills, "
                                "and driver shutdown on an uncaught "
                                "error — a chaos casualty leaves a "
                                "corpse `rtpu autopsy` can read"),
    "debug_bundle_dir": (str, "",
                         "directory auto-captured debug bundles are "
                         "written to (default: the session dir when "
                         "known, else the system temp dir)"),
    # --- debugging / stall detection ---
    "stall_detector_interval_s": (float, 5.0,
                                  "control-plane stall sweep period; "
                                  "0 disables the detector"),
    "stall_pending_threshold_s": (float, 30.0,
                                  "warn (TASK_STALL event, with a "
                                  "diagnosed cause) when a task sits in "
                                  "a pending state this long; 0 disables"),
    "stall_running_threshold_s": (float, 300.0,
                                  "warn when a task has been RUNNING "
                                  "this long; 0 disables"),
    "profiler_max_duration_s": (float, 60.0,
                                "hard cap on one `rtpu profile` "
                                "sampling run"),
    "profiler_default_interval_ms": (int, 10,
                                     "default sampling period of the "
                                     "wall-clock profiler"),
    # --- protocol / wire transport ---
    "socket_send_buffer_bytes": (int, 1 << 21,
                                 "SO_SNDBUF requested for control-plane "
                                 "sockets"),
    "socket_recv_buffer_bytes": (int, 1 << 21,
                                 "SO_RCVBUF requested for control-plane "
                                 "sockets"),
    "transport_max_batch_msgs": (int, 128,
                                 "max messages the connection writer "
                                 "coalesces into one BATCH frame"),
    "transport_max_batch_bytes": (int, 1 << 20,
                                  "approximate payload cap of one "
                                  "coalesced BATCH frame (estimated "
                                  "pre-pickle; large messages get their "
                                  "own frame)"),
    "transport_queue_depth": (int, 1024,
                              "bounded per-connection send queue; "
                              "producers block above this depth "
                              "(backpressure)"),
    "transport_oob_threshold_bytes": (int, 64 << 10,
                                      "pickle-5 buffers >= this ship "
                                      "out-of-band as zero-copy iovecs "
                                      "instead of inside the pickle "
                                      "stream"),
    "rpc_inline_chunk_bytes": (int, 1 << 20, "frame chunking for large messages"),
    # --- collectives ---
    "collective_chunk_bytes": (int, 1 << 20,
                               "ring collectives split tensors into chunks "
                               "of this size so chunk k+1 transmits while "
                               "chunk k reduces (pipelining grain)"),
    "collective_tree_threshold_bytes": (int, 32 << 10,
                                        "payloads below this use a binomial "
                                        "tree allreduce (latency-bound "
                                        "regime) instead of the ring "
                                        "(bandwidth-bound regime)"),
    "collective_timeout_s": (float, 60.0,
                             "default deadline of one collective call; a "
                             "rank that dies mid-collective surfaces a "
                             "TimeoutError on every survivor within this"),
    "collective_call_ttl_s": (float, 120.0,
                              "coordinator-side sweep: call records and "
                              "mailbox posts older than this whose group "
                              "members never completed/acked are dropped "
                              "(a timed-out rank must not leak its "
                              "partial contribution forever)"),
    "collective_p2p_enabled": (bool, True,
                               "route collective payloads peer-to-peer "
                               "over the zero-copy transport; off = "
                               "degenerate fallback through the "
                               "coordinator actor (control plane)"),
    "collective_algo": (str, "auto",
                        "force one collective schedule (ring | tree | "
                        "hierarchical | star); auto consults the "
                        "size x topology x dtype selection table "
                        "(_select_schedule) per call"),
    "collective_hierarchical_threshold_bytes": (int, 256 << 10,
                                                "payloads at/above this on a "
                                                "multi-node group with "
                                                "co-located ranks run the "
                                                "two-level hierarchical "
                                                "schedule (intra-node reduce "
                                                "-> inter-node leader ring "
                                                "-> intra-node broadcast); "
                                                "below it the flat ring's "
                                                "fewer staging hops win"),
    "collective_wire_dtype": (str, "exact",
                              "wire precision of INTER-node hops in "
                              "hierarchical reductions: exact (default, "
                              "bit-exact) | bf16 (~2x wire reduction) | "
                              "int8-blockscale (~4x, per-block max-abs "
                              "scales). Intra-node hops and non-reduction "
                              "ops always stay exact"),
    "collective_quant_block_elems": (int, 256,
                                     "block size (elements) of the "
                                     "int8-blockscale wire format; one "
                                     "float32 scale rides along per "
                                     "block"),
    "collective_reform_mode": (str, "replace",
                               "how a group heals after a dead-rank "
                               "verdict: replace (wait for a restarted "
                               "rank to re-enter with the same rank) | "
                               "shrink (contract the world to the "
                               "survivors, renumbered contiguously, "
                               "once arrivals quiesce for the grace "
                               "window)"),
    "collective_reform_retries": (int, 2,
                                  "reform+re-issue attempts the "
                                  "fault-tolerant wrappers "
                                  "(ft_allreduce / FaultTolerantGroup) "
                                  "make per call before surfacing the "
                                  "failure"),
    "collective_reform_timeout_s": (float, 30.0,
                                    "deadline of one reform round: in "
                                    "replace mode, how long survivors "
                                    "wait for the restarted "
                                    "replacement rank to re-join "
                                    "before the reform itself fails "
                                    "with a clear error"),
    "collective_reform_grace_s": (float, 5.0,
                                  "shrink mode: the round resolves "
                                  "once no new rank has re-joined for "
                                  "this long — stragglers that arrive "
                                  "within the window stay members"),
    "actor_checkpoint_interval_s": (float, 0.0,
                                    "checkpoint an actor defining "
                                    "save_checkpoint() when at least "
                                    "this many seconds have passed "
                                    "since the last capture, checked "
                                    "at each call completion (the "
                                    "worker's safe quiescent point — "
                                    "idle actors mutate no state, so "
                                    "no between-call tick is needed); "
                                    "rides the same seq-guarded plane "
                                    "path as the call-count trigger. "
                                    "0 disables the time trigger"),
    "actor_checkpoint_interval_calls": (int, 0,
                                        "checkpoint an actor defining "
                                        "save_checkpoint() every N "
                                        "completed calls (captured "
                                        "BEFORE the call's result is "
                                        "reported, so an observed "
                                        "completion implies checkpoint "
                                        "durability); 0 = only on "
                                        "demand via "
                                        "ray_tpu.actor_checkpoint()"),
    "flight_recorder_capacity": (int, 4096,
                                 "event slots in the per-process "
                                 "collective flight-recorder ring "
                                 "(always-on, lock-free appends); 0 "
                                 "disables recording AND the timeout "
                                 "hang diagnosis"),
    "coll_progress_timeout_s": (float, 2.0,
                                "deadline for one COLL_PROGRESS "
                                "watermark fan-out (hang diagnosis; "
                                "answered on reader threads, so even "
                                "wedged ranks reply within this)"),
    "object_transfer_chunk_bytes": (int, 8 << 20,
                                    "cross-host object pulls stream in "
                                    "chunks of this size (reference: "
                                    "object_manager chunked Push/Pull)"),
    "grpc_equivalent_port": (int, 0, "tcp port for the head control plane (0 = unix socket)"),
    # --- serve request observability ---
    "request_log_capacity": (int, 256,
                             "per-replica structured access-log ring "
                             "slots (request_id, route, status, "
                             "latency, queue wait, batch size); 0 "
                             "disables the whole request-observability "
                             "plane — no request metadata attaches, no "
                             "ingress/queue/replica spans, no "
                             "digests, restoring the pre-PR request "
                             "hot path"),
    "serve_slow_request_threshold_s": (float, 1.0,
                                       "serve requests slower than "
                                       "this are promoted to a "
                                       "SLOW_REQUEST cluster event "
                                       "(errors always promote as "
                                       "REQUEST_ERROR); 0 disables "
                                       "slow-request promotion"),
    # --- lineage ---
    "max_lineage_bytes": (int, 100 * (1 << 20),
                          "lineage footprint cap (reference: task_manager.h:180)"),
    # --- logging ---
    "log_to_driver": (bool, True, "forward worker stdout/stderr to the driver"),
}


# Renamed knobs: old name -> canonical name. Old env vars
# (RTPU_<OLD_NAME>) and _system_config keys keep working; attribute
# reads of the old name resolve to the canonical value.
_ALIASES: Dict[str, str] = {
    "max_inline_object_bytes": "object_store_shm_threshold_bytes",
    "object_spilling_threshold": "object_store_spill_threshold",
    "spill_directory": "object_store_spill_dir",
}


class _Config:
    """Process-wide config singleton. Read via attribute access."""

    def __init__(self):
        self._values: Dict[str, Any] = {}
        self.reload()

    def reload(self, system_config: Dict[str, Any] | None = None) -> None:
        values: Dict[str, Any] = {}
        for name, (typ, default, _help) in _CONFIG_DEFS.items():
            raw = os.environ.get(_ENV_PREFIX + name.upper())
            if raw is not None:
                values[name] = self._parse(typ, raw)
            else:
                values[name] = default
        for old, new in _ALIASES.items():
            raw = os.environ.get(_ENV_PREFIX + old.upper())
            if (raw is not None
                    and os.environ.get(_ENV_PREFIX + new.upper()) is None):
                values[new] = self._parse(_CONFIG_DEFS[new][0], raw)
        if system_config:
            for key, val in system_config.items():
                key = _ALIASES.get(key, key)
                if key not in _CONFIG_DEFS:
                    raise ValueError(f"unknown config key: {key}")
                values[key] = val
        self._values = values

    @staticmethod
    def _parse(typ, raw: str):
        if typ is bool:
            return raw.lower() in ("1", "true", "yes", "on")
        if typ in (int, float, str):
            return typ(raw)
        return json.loads(raw)

    def __getattr__(self, name: str):
        try:
            return self._values[_ALIASES.get(name, name)]
        except KeyError:
            raise AttributeError(name) from None

    def __reduce__(self):
        # the singleton must never ship by value: function/class blobs
        # pickled by value (cloudpickle) capture any CONFIG global
        # their bodies reference, and a value-pickled _Config would (a)
        # hit __getattr__ recursion before _values exists on unpickle
        # and (b) freeze the ORIGIN process's table into the
        # destination. Resolve to the destination's own singleton.
        return (_current_config, ())

    def dump(self) -> Dict[str, Any]:
        return dict(self._values)


def _current_config() -> "_Config":
    return CONFIG


CONFIG = _Config()


def fw_importable_without_path() -> bool:
    """True when ray_tpu is pip-installed (editable or wheel), i.e. a
    spawned interpreter can ``import ray_tpu`` with no PYTHONPATH help.
    Dev checkouts run via cwd/PYTHONPATH return False and worker spawn
    injects the framework root (reference: ``python/setup.py:103`` —
    the reference is always installed; here both modes work)."""
    global _FW_INSTALLED
    if _FW_INSTALLED is None:
        try:
            import importlib.metadata as _md
            _md.distribution("ray-tpu")
            _FW_INSTALLED = True
        except Exception:
            _FW_INSTALLED = False
    return _FW_INSTALLED


_FW_INSTALLED = None
