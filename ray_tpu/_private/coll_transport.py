"""Peer-to-peer collective chunk plane: per-process mailbox + routed sends.

The data plane under ``comm/collective.py``'s ring/tree schedules. A rank
is addressed by its **endpoint** ``(node_id_bytes, worker_id_bytes)``;
``send`` ships one ``COLL_ROUTE`` frame to this process's node, which
delivers it to the destination process's connection — directly when the
destination lives on the same node, across the node plane (``COLL_FWD``)
otherwise. Tensor payloads are numpy arrays, which ride each hop
out-of-band (pickle protocol-5 iovecs) once they clear
``transport_oob_threshold_bytes`` — zero-copy end to end.

Completion is driven by connection reader threads: an arriving
``COLL_DELIVER`` frame is deposited here (``deposit``) under a condition
variable that wakes the rank thread blocked in ``wait``. There is no
polling anywhere on this path — a waiter sleeps until its chunk arrives
or its deadline passes.

**Epoch fencing** (group self-healing): when a collective group reforms
after a rank death, the failing epoch is ``fence``d *before* the
survivors re-join — its undelivered chunks are dropped on the spot, and
any chunk of that epoch still in flight (a dead rank's last sends, a
survivor's pipelined traffic) is refused at ``deposit`` time instead of
parked. A stale-epoch chunk can therefore never be delivered into (or
accumulate beside) the reformed epoch's calls, and teardown never waits
on the TTL sweep.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from . import context
from . import fieldsan
from . import flight_recorder
from . import locksan
from . import telemetry
from .config import CONFIG

M_COLL_CHUNKS = telemetry.define(
    "counter", "rtpu_collective_chunks_total",
    "Peer-to-peer collective chunks sent by this rank")
M_COLL_WIRE_BYTES = telemetry.define(
    "counter", "rtpu_collective_wire_bytes_total",
    "Payload bytes this rank sent peer-to-peer for collectives (ring "
    "allreduce: ~2x tensor size per rank, independent of world size)")
M_COLL_INFLIGHT = telemetry.define(
    "gauge", "rtpu_collective_inflight_chunks",
    "Collective chunks delivered to this process but not yet consumed "
    "by a waiting rank thread")
M_COLL_HOP = telemetry.define(
    "histogram", "rtpu_collective_hop_seconds",
    "Time a rank thread spent blocked waiting for one collective chunk "
    "to arrive, tagged by schedule phase — per-rank hop-latency skew "
    "makes chronic stragglers visible before they become hangs")
M_COLL_FENCED = telemetry.define(
    "counter", "rtpu_collective_fenced_chunks_total",
    "Stale-epoch collective chunks dropped by the reform fence (swept "
    "from the mailbox at fence time, or refused on arrival) — traffic "
    "of a failed epoch that must never reach the reformed group's calls")

_lock = locksan.lock("coll.mailbox")
_cond = locksan.condition("coll.mailbox", _lock)
_slots: Dict[tuple, Any] = {}
# arrival time per undelivered chunk, for the stale sweep: a rank that
# timed out (or died) mid-collective leaves chunks addressed to keys no
# waiter will ever consume — without a TTL they'd sit here until
# destroy_collective_group, growing without bound across retried calls
_born: Dict[tuple, float] = {}
_next_sweep = [0.0]             # guarded by _lock
# fenced epochs per group (guarded by _lock): chunks keyed with a fenced
# (group, epoch) prefix are dropped instead of deposited. Bounded both
# ways — per group (a group that reformed more than maxlen times has
# long stopped receiving its oldest epochs' traffic) and across groups
# (destroy fences on every teardown, so per-job group-name churn must
# not grow the dict for the process lifetime; evicted groups' stale
# stragglers fall back to the TTL sweep)
_FENCED_PER_GROUP = 8
_FENCED_GROUPS = 64
_fenced: Dict[str, deque] = {}

# plain per-process counters for tests/diagnostics (no shard-lock cost);
# single-writer per field in practice (the rank thread / reader thread).
# sent_remote_* count only chunks addressed to a DIFFERENT node — the
# traffic that actually crosses the node plane (COLL_FWD), which is what
# hierarchical schedules and the quantized wire format exist to shrink.
_stats = {"sent_chunks": 0, "sent_bytes": 0, "recv_chunks": 0,
          "recv_bytes": 0, "sent_remote_chunks": 0,
          "sent_remote_bytes": 0, "fenced_chunks": 0}


def payload_nbytes(payload) -> int:
    """Wire-payload size of one chunk: ndarray / QuantChunk ``nbytes``,
    recursed through tuples/lists (hierarchical allgather ships bundles
    of per-rank arrays in one mailbox message)."""
    nbytes = getattr(payload, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    if isinstance(payload, (tuple, list)):
        return sum(payload_nbytes(v) for v in payload)
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return len(payload)
    return 0


def local_endpoint() -> Optional[Tuple[bytes, bytes]]:
    """This process's rank address, or None when no runtime client is
    connected (the group then degrades to the coordinator fallback)."""
    client = context.current_client
    if client is None or client.node_id is None:
        return None
    return (client.node_id.binary(), client.worker_id.binary())


def send(dest: Tuple[bytes, bytes], key: tuple, payload,
         group: str = "", op: str = "") -> None:
    """Route one chunk to ``dest``'s mailbox under ``key``. Fire and
    forget: delivery failures surface as the receiver's deadline."""
    from . import protocol as P
    client = context.require_client()
    nbytes = payload_nbytes(payload)
    flight_recorder.note_send(key, nbytes)
    client.conn.send((P.COLL_ROUTE, (dest[0], dest[1], key, payload)))
    _stats["sent_chunks"] += 1
    _stats["sent_bytes"] += nbytes
    if client.node_id is not None and dest[0] != client.node_id.binary():
        _stats["sent_remote_chunks"] += 1
        _stats["sent_remote_bytes"] += nbytes
    tags = (("group", group), ("op", op))
    telemetry.counter_inc(M_COLL_CHUNKS, 1.0, tags)
    if nbytes:
        telemetry.counter_inc(M_COLL_WIRE_BYTES, float(nbytes), tags)


def deposit(key: tuple, value) -> None:
    """Reader-thread side: park an arrived chunk and wake waiters.
    Chunks of a fenced (group, epoch) — traffic of an epoch a reform
    already superseded — are dropped here instead of parked: no waiter
    under the new epoch can ever key-match them, and without the fence
    they would sit in the mailbox until the TTL sweep."""
    now = time.monotonic()
    # ring-only recorder hook BEFORE taking the mailbox lock (lock-free
    # append; the reader thread must never nest another lock here)
    flight_recorder.note_deliver(key, payload_nbytes(value))
    with _cond:
        if len(key) >= 2:
            fenced = _fenced.get(key[0])
            if fenced is not None and key[1] in fenced:
                _stats["fenced_chunks"] += 1
                telemetry.counter_inc(M_COLL_FENCED, 1.0,
                                      (("group", str(key[0])),))
                return
        _slots[key] = value
        _born[key] = now
        if now >= _next_sweep[0]:
            ttl = CONFIG.collective_call_ttl_s
            _next_sweep[0] = now + max(1.0, ttl / 4)
            for k in [k for k, b in _born.items() if now - b > ttl]:
                _slots.pop(k, None)
                _born.pop(k, None)
        n = len(_slots)
        _cond.notify_all()
    _stats["recv_chunks"] += 1
    _stats["recv_bytes"] += payload_nbytes(value)
    telemetry.gauge_set(M_COLL_INFLIGHT, float(n))


def wait(key: tuple, deadline: float, what: str = "collective chunk"):
    """Block until ``key``'s chunk arrives; raises TimeoutError at the
    deadline (a dead peer must not hang the survivors)."""
    t0 = time.monotonic()
    flight_recorder.note_wait(key)
    with _cond:
        while key not in _slots:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"timed out waiting for {what} {key!r} — a group "
                    "member is dead, wedged, or running a mismatched "
                    "collective schedule")
            _cond.wait(remaining)
        value = _slots.pop(key)
        _born.pop(key, None)
        n = len(_slots)
    nbytes = payload_nbytes(value)
    flight_recorder.note_recv(key, nbytes)
    _okey, phase = flight_recorder.parse_key(key)
    telemetry.hist_observe(M_COLL_HOP, time.monotonic() - t0,
                           (("phase", phase),))
    telemetry.gauge_set(M_COLL_INFLIGHT, float(n))
    return value


def flush() -> None:
    """Block until every chunk this process queued on its node link has
    reached the socket. Schedules send ZERO-COPY views of caller-owned
    (and returned) arrays; under send-queue contention those views are
    pickled later by whichever thread drains the queue, so a collective
    only becomes safe to return from — letting the caller mutate its
    tensors — once the link is flushed. Uncontended (the common case)
    this is one try-lock."""
    client = context.current_client
    if client is not None:
        client.conn.flush()


def fence(group: str, epoch: str) -> int:
    """Fence one (group, epoch): drop its undelivered chunks NOW and
    refuse any further deposit keyed with it. Called by the reform path
    BEFORE the survivors re-join (so nothing of the failing epoch can
    cross into the new one) and by group teardown (so a dead member's
    stranded traffic never waits on the TTL sweep). Returns the number
    of chunks dropped at fence time; late arrivals count into
    ``stats()["fenced_chunks"]`` as they are refused."""
    dropped = 0
    with _cond:
        fenced = _fenced.get(group)
        if fenced is None:
            fenced = _fenced[group] = deque(maxlen=_FENCED_PER_GROUP)
            while len(_fenced) > _FENCED_GROUPS:
                _fenced.pop(next(iter(_fenced)))
        if epoch not in fenced:
            fenced.append(epoch)
        for k in [k for k in _slots if k[:2] == (group, epoch)]:
            del _slots[k]
            _born.pop(k, None)
            dropped += 1
        if dropped:
            _stats["fenced_chunks"] += dropped
            telemetry.counter_inc(M_COLL_FENCED, float(dropped),
                                  (("group", group),))
        telemetry.gauge_set(M_COLL_INFLIGHT, float(len(_slots)))
    return dropped


def fenced_epochs(group: str) -> Tuple[str, ...]:
    """Test/debug surface: the epochs currently fenced for a group."""
    with _lock:
        return tuple(_fenced.get(group) or ())


def pending_keys() -> Tuple[tuple, ...]:
    """Test/debug surface: keys of every undelivered chunk (the chaos
    tests assert no stale-epoch key survives a reform)."""
    with _lock:
        return tuple(_slots)


def drop_call(group: str, epoch: str, seq) -> None:
    """Discard undelivered chunks of ONE timed-out call (keys lead with
    (group, epoch, seq)): nothing will ever consume them, and without
    this the ``rtpu_collective_inflight_chunks`` gauge stays elevated
    for up to ``collective_call_ttl_s`` after every failed collective —
    the gauge must return to 0 when the failure is handled, not when
    the sweep happens by."""
    prefix = (group, epoch, seq)
    with _cond:
        for k in [k for k in _slots if k[:3] == prefix]:
            del _slots[k]
            _born.pop(k, None)
        telemetry.gauge_set(M_COLL_INFLIGHT, float(len(_slots)))


def drop_group(group: str, epoch: str) -> None:
    """Discard undelivered chunks of a destroyed group (keys lead with
    (group, epoch)) so name reuse can never consume stale traffic."""
    with _cond:
        for k in [k for k in _slots
                  if k[:2] == (group, epoch)]:
            del _slots[k]
            _born.pop(k, None)
        telemetry.gauge_set(M_COLL_INFLIGHT, float(len(_slots)))


def stats() -> Dict[str, int]:
    """Per-process wire counters (tests assert ring traffic is O(size)
    per rank, not O(world * size) through one process)."""
    out = dict(_stats)
    with _lock:
        out["pending"] = len(_slots)
    return out


# guarded-by plane: wrap the declared module-level mailbox state in
# checking proxies (no-op when RTPU_FIELDSAN is off)
fieldsan.instrument_module(globals(), "coll_transport")
