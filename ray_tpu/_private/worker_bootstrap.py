"""Worker bootstrap for ``pip`` runtime environments.

Spawned instead of the worker module when a task's runtime_env asks for
pip packages: builds (or reuses) a cached virtualenv, then ``exec``s the
real worker under the venv's interpreter. Runs in the worker process so
an environment build never blocks the node's dispatcher; a failed build
exits nonzero, which the node's startup-failure reaper turns into
``RuntimeEnvSetupError`` for the pending tasks (the same path a broken
``working_dir`` takes).

Reference analogue: the per-node runtime-env agent building pip/conda
envs (``python/ray/_private/runtime_env/agent/runtime_env_agent.py:281``
and ``runtime_env/pip.py``) keyed and cached by URI. The venv is created
with ``--system-site-packages`` so the image's baked-in stack (jax,
numpy, ...) stays importable — the reference's pip env inherits the base
environment the same way.
"""

from __future__ import annotations

import fcntl
import glob
import json
import os
import shutil
import site
import subprocess
import sys


def _build_venv(venv_dir: str, packages: list, options: list) -> None:
    subprocess.run(
        [sys.executable, "-m", "venv", "--system-site-packages", venv_dir],
        check=True)
    # When THIS interpreter is itself a venv (common for hermetic
    # images), the child venv's --system-site-packages exposes the BASE
    # python's site-packages, not this venv's — so the image's baked-in
    # stack (jax, cloudpickle, ...) would vanish. A .pth file appends the
    # parent's site dirs after the child's own, so pip-installed packages
    # still shadow inherited ones.
    parent_sites = [p for p in site.getsitepackages() if os.path.isdir(p)]
    for child_site in glob.glob(
            os.path.join(venv_dir, "lib", "python*", "site-packages")):
        with open(os.path.join(child_site, "_rtpu_parent_env.pth"),
                  "w") as f:
            f.write("\n".join(parent_sites) + "\n")
    venv_py = os.path.join(venv_dir, "bin", "python")
    if packages:
        subprocess.run(
            [venv_py, "-m", "pip", "install",
             "--no-warn-script-location", *options, *packages],
            check=True)


def ensure_venv(cache_dir: str, key: str, packages: list,
                options: list) -> str:
    """Build-or-reuse the venv for ``key``; returns its python path.

    Concurrent spawns of the same env serialize on a file lock; only the
    first builds. A crash mid-build leaves no ready marker, so the next
    holder wipes the partial tree and rebuilds.
    """
    os.makedirs(cache_dir, exist_ok=True)
    venv_dir = os.path.join(cache_dir, f"venv-{key}")
    marker = os.path.join(venv_dir, ".rtpu_ready")
    venv_py = os.path.join(venv_dir, "bin", "python")
    with open(os.path.join(cache_dir, f"venv-{key}.lock"), "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if not os.path.exists(marker):
            if os.path.isdir(venv_dir):
                shutil.rmtree(venv_dir)
            _build_venv(venv_dir, packages, options)
            with open(marker, "w") as f:
                f.write(json.dumps({"packages": packages}))
    return venv_py


def main() -> None:
    spec = json.loads(os.environ.pop("RTPU_PIP_SPEC"))
    cache_dir = os.environ.pop("RTPU_ENV_CACHE_DIR")
    try:
        venv_py = ensure_venv(cache_dir, spec["key"], spec["packages"],
                              spec.get("options", []))
    except subprocess.CalledProcessError as e:
        print(f"[rtpu] pip runtime_env build failed: {e}", file=sys.stderr)
        sys.exit(1)
    os.execv(venv_py, [venv_py, "-m", "ray_tpu._private.worker",
                       *sys.argv[1:]])


if __name__ == "__main__":
    main()
