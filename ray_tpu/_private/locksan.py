"""Lock registry + opt-in runtime lock-order sanitizer.

Every lock the runtime constructs goes through the factories here
(``lock()`` / ``rlock()`` / ``condition()``) under a **declared name**
from ``REGISTRY`` — the single Python source of truth for the lock
hierarchy documented in DESIGN.md ("Threading model & lock hierarchy")
and enforced statically by ``scripts/check_concurrency.py``. Reference
analogue: the TSan/deadlock annotations the C++ core wires into CI
(``src/ray/util/mutex_protected.h`` + sanitizer builds); a Python
runtime gets the same class of coverage from this module plus the AST
analyzer.

Normally (``RTPU_LOCKSAN`` unset/0) the factories return plain
``threading`` primitives — zero overhead beyond one function call at
construction. With ``RTPU_LOCKSAN=1`` (tier-1 sets this in conftest)
every lock is wrapped by ``_SanLock``, which on each **blocking**
acquire:

- checks the acquisition against the declared hierarchy: while holding
  a registered lock of level L, only strictly-greater levels may be
  acquired (re-entry of the same ``rlock`` object is exempt; re-entry
  of a plain ``lock`` is reported as a guaranteed self-deadlock);
- records the (held → acquired) edge in a process-wide acquisition-
  order graph and searches it for a cycle **before** blocking, so an
  A→B / B→A inversion across two threads is reported (and in ``raise``
  mode, refused) at the second thread's acquire — before the threads
  wedge;
- keeps the acquisition stack of every first-seen edge so a violation
  report shows both sides of the inversion.

Try-locks and timed acquires only update held-state (they cannot
deadlock by themselves and the transport's opportunistic-drainer
try-lock pattern must stay silent). Violations go to
``violations()`` and stderr (``RTPU_LOCKSAN_MODE=log``, the default)
or raise ``LockOrderViolation`` at the acquire site
(``RTPU_LOCKSAN_MODE=raise`` or ``set_mode("raise")``).

Unregistered names (tests, scratch locks) are allowed at runtime: they
skip the hierarchy check but fully participate in cycle detection. The
static analyzer is what rejects unregistered names *inside* ray_tpu/.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional

__all__ = [
    "REGISTRY", "lock", "rlock", "condition", "enabled", "set_mode",
    "violations", "clear_violations", "LockOrderViolation",
]

# --------------------------------------------------------------- registry
#
# name -> (module, kind, level, what it protects).
#
# Levels define the global acquisition order: a thread holding a lock of
# level L may only block-acquire strictly greater levels. Independent
# leaf locks (never co-held with anything) still get distinct levels so
# a future nesting forces a conscious ordering decision instead of
# silently passing. The DESIGN.md table and this dict are cross-checked
# by check_concurrency.py (both directions), as are the construction
# sites.

REGISTRY: Dict[str, tuple] = {
    # --- client submission/refcount plane (outermost: held across sends
    # --- by design, see the flush_refs/flush_submissions FIFO comments)
    "client.edge_flush": ("_private/client.py", "lock", 10,
                          "ref-edge batch FIFO: held across take-and-send"),
    "client.sub": ("_private/client.py", "lock", 12,
                   "submission buffer; held across the batch send (FIFO)"),
    "client.ref": ("_private/client.py", "lock", 14,
                   "local per-object refcounts + edge buffer"),
    "client.gen_credit": ("_private/client.py", "lock", 16,
                          "streaming-generator producer credit table"),
    # --- control plane
    "gcs.plane": ("_private/gcs.py", "rlock", 20,
                  "every GlobalControlPlane registry/table"),
    "gcs.journal": ("_private/gcs_storage.py", "lock", 25,
                    "journal file handle (append/compact/close)"),
    # --- node service (cross-thread state next to the dispatcher)
    "node.res": ("_private/node.py", "lock", 30,
                 "resources_available + PG reservations + TPU slots"),
    "node.debug": ("_private/node.py", "lock", 32,
                   "in-flight debug-collection futures/tokens"),
    "gcs_server.conns": ("_private/gcs_service.py", "lock", 34,
                         "GcsServer conn/subscription tables"),
    "gcs_client.subs": ("_private/gcs_service.py", "lock", 36,
                        "RemoteControlPlane subscriber lists"),
    # --- object plane
    "store.entries": ("_private/object_store.py", "rlock", 38,
                      "store entry table, budget, arena quarantine"),
    "store.reader_segments": ("_private/object_store.py", "lock", 40,
                              "per-process attached-segment cache"),
    # --- collective data plane
    "coll.mailbox": ("_private/coll_transport.py", "condition", 42,
                     "per-process chunk mailbox; condvar wakes waiters"),
    "coll.recorder": ("_private/flight_recorder.py", "lock", 43,
                      "flight-recorder group/op tables (ring appends "
                      "are lock-free; this guards begin/end/snapshot)"),
    # --- independent leaves (never co-held today; distinct levels so a
    # --- future nesting trips the sanitizer instead of passing silently)
    "events.file": ("_private/events.py", "lock", 44,
                    "events JSONL append serialization"),
    "debug.bundle": ("_private/debug_bundle.py", "lock", 45,
                     "auto-capture once-per-reason set"),
    "jobs.manager": ("job/manager.py", "lock", 46,
                     "job records + supervisor proc table"),
    "serve.controller": ("serve/controller.py", "lock", 48,
                         "deployment target/replica state"),
    "serve.handle": ("serve/handle.py", "lock", 50,
                     "per-handle replica list + in-flight counters"),
    "serve.batcher": ("serve/batching.py", "lock", 52,
                      "batcher thread liveness"),
    "serve.multiplex": ("serve/multiplex.py", "lock", 54,
                        "per-replica model LRU"),
    "serve.replica_depth": ("serve/replica.py", "lock", 56,
                            "replica queue-depth counter"),
    "collective.groups": ("comm/collective.py", "lock", 58,
                          "per-process collective group registry"),
    "workflow.registry": ("workflow/__init__.py", "lock", 60,
                          "workflow storage create/resume exclusion"),
    "autoscaler.provider": ("autoscaler/node_provider.py", "lock", 62,
                            "fake provider node list"),
    "api.remote_fn": ("api.py", "lock", 64,
                      "lazy function blob export"),
    "api.actor_class": ("api.py", "lock", 66,
                        "lazy actor class blob export"),
    "api.actor_seq": ("api.py", "lock", 68,
                      "per-handle actor call sequence numbers"),
    "tracing.buffer": ("util/tracing.py", "lock", 70,
                       "finished-span buffer"),
    "tqdm.render": ("util/tqdm_ray.py", "lock", 72,
                    "driver-side progress render state"),
    "native.arena_cache": ("_private/native.py", "lock", 74,
                           "per-process ArenaReader cache"),
    "native.lib": ("_private/native.py", "lock", 76,
                   "one-time native library build/load"),
    # --- transport (innermost of the send path; the drainer protocol
    # --- holds conn.flush while failing futures through on_send_error)
    "conn.flush": ("_private/protocol.py", "lock", 85,
                   "active-drainer exclusion (held across sendmsg)"),
    "rpc.futures": ("_private/rpc.py", "lock", 87,
                    "RpcChannel req-id -> future table"),
    "client.req": ("_private/client.py", "lock", 88,
                   "CoreClient req-id -> future table"),
    "conn.queue": ("_private/protocol.py", "lock", 90,
                   "per-connection send queue + broken/closing flags"),
    # --- telemetry (innermost everywhere: record calls happen under
    # --- arbitrary runtime locks)
    "telemetry.meta": ("_private/telemetry.py", "lock", 93,
                       "metric metadata registry"),
    "telemetry.runtime": ("_private/telemetry.py", "lock", 94,
                          "flusher/sampler lifecycle + node registry"),
    "telemetry.shard": ("_private/telemetry.py", "lock", 95,
                        "one metrics shard (8 instances)"),
}

# ----------------------------------------------------------- guarded-by
#
# FIELDS: declared shared-state ownership — which guard protects each
# multi-thread-touched attribute (the data-side complement of REGISTRY,
# reference: Clang GUARDED_BY annotations across src/ray/common/).
# Key: "<module short name>.<Class>.<attr>" for instance fields,
# "<module short name>.<name>" for module-level state. Value:
#
#   "<lock name>"        guarded by that REGISTRY lock (reads+writes
#                        pair-checked at runtime; every lexical write
#                        must sit under `with <lock>` or a
#                        `# concurrency: requires(<lock>)` function —
#                        rule (h) of scripts/check_concurrency.py)
#   "thread:<pat>"       write-confined to threads whose name contains
#                        <pat>; cross-thread reads are tolerated dirty
#                        reads (GIL-atomic), a foreign write is a
#                        violation
#   "<lock name>|static" guarded by that lock, verified by the STATIC
#                        pass only — the documented hot-path exemption
#                        (per-message transport innards, metric shards)
#                        where a per-access runtime hook costs more
#                        than the residual risk of the small audited
#                        module it guards
#   "atomic:<reason>"    deliberately lock-free shared state relying on
#                        GIL-atomic single ops; declared so the
#                        undeclared-candidate inference can't rot, not
#                        instrumented
#
# Runtime checking lives in _private/fieldsan.py (RTPU_FIELDSAN=1, on
# in tier-1); classes/modules opt in with @fieldsan.guarded /
# fieldsan.instrument_module, which rule (h) verifies. DESIGN.md
# "Shared-state ownership map" mirrors this table (cross-checked both
# directions).

FIELDS: Dict[str, str] = {
    # --- control plane: every registry/table under the one plane lock
    "gcs.GlobalControlPlane.nodes": "gcs.plane",
    "gcs.GlobalControlPlane.actors": "gcs.plane",
    "gcs.GlobalControlPlane.named_actors": "gcs.plane",
    "gcs.GlobalControlPlane.jobs": "gcs.plane",
    "gcs.GlobalControlPlane.kv": "gcs.plane",
    "gcs.GlobalControlPlane.placement_groups": "gcs.plane",
    "gcs.GlobalControlPlane.directory": "gcs.plane",
    "gcs.GlobalControlPlane.gen_streams": "gcs.plane",
    "gcs.GlobalControlPlane.pending_pgs": "gcs.plane",
    "gcs.GlobalControlPlane.task_events": "gcs.plane",
    "gcs.GlobalControlPlane.cluster_events": "gcs.plane",
    "gcs.GlobalControlPlane.lifecycle_events": "gcs.plane",
    "gcs.GlobalControlPlane._events_evicted": "gcs.plane",
    "gcs.GlobalControlPlane._history_interval_digests": "gcs.plane",
    "gcs.GlobalControlPlane._history_last": "gcs.plane",
    "gcs.GlobalControlPlane.spans": "gcs.plane",
    "gcs.GlobalControlPlane.metrics_counters": "gcs.plane",
    "gcs.GlobalControlPlane.metrics_gauges": "gcs.plane",
    "gcs.GlobalControlPlane._gauge_tombstones": "gcs.plane",
    "gcs.GlobalControlPlane.metrics_hists": "gcs.plane",
    "gcs.GlobalControlPlane.metrics_digests": "gcs.plane",
    "gcs.GlobalControlPlane.metrics_meta": "gcs.plane",
    "gcs.GlobalControlPlane._metrics_dropped_keys": "gcs.plane",
    "gcs.GlobalControlPlane._metrics_conflict_keys": "gcs.plane",
    "gcs.GlobalControlPlane._subscribers": "gcs.plane",
    "gcs.GlobalControlPlane.ref_holders": "gcs.plane",
    "gcs.GlobalControlPlane.ref_pins": "gcs.plane",
    "gcs.GlobalControlPlane._task_arg_refs": "gcs.plane",
    "gcs.GlobalControlPlane._task_pin_owner": "gcs.plane",
    "gcs.GlobalControlPlane._freed_early": "gcs.plane",
    "gcs.GlobalControlPlane._contained_pins": "gcs.plane",
    "gcs.GlobalControlPlane._contained_pending": "gcs.plane",
    "gcs.GlobalControlPlane._zero_pending": "gcs.plane",
    "gcs.GlobalControlPlane.lineage": "gcs.plane",
    "gcs.GlobalControlPlane._lineage_live": "gcs.plane",
    "gcs.GlobalControlPlane._lineage_bytes": "gcs.plane",
    "gcs.GlobalControlPlane._sealed_once": "gcs.plane",
    "gcs.GlobalControlPlane._reconstruct_claims": "gcs.plane",
    "gcs.GlobalControlPlane._reconstruct_counts": "gcs.plane",
    "gcs.GlobalControlPlane.actor_checkpoints": "gcs.plane",
    "gcs.GlobalControlPlane._actor_reroutes": "gcs.plane",
    "gcs.GlobalControlPlane._stall_last_sweep": "gcs.plane",
    "gcs.GlobalControlPlane._stall_warned": "gcs.plane",
    "gcs.GlobalControlPlane.obj_provenance": "gcs.plane",
    "gcs.GlobalControlPlane._leaks": "gcs.plane",
    "gcs.GlobalControlPlane._pinned_zero_since": "gcs.plane",
    "gcs.GlobalControlPlane._leak_warned": "gcs.plane",
    "gcs.GlobalControlPlane._leak_last_sweep": "gcs.plane",
    "gcs.GlobalControlPlane._storage": "gcs.plane",
    # --- metrics-history rings: owned by the plane, serialized under
    # --- its lock (standalone instances in unit tests are
    # --- single-threaded; the live plane routes queries through
    # --- gcs.metrics_history_query)
    "history.MetricsHistory.levels": "gcs.plane",
    "history.MetricsHistory.total_bytes": "gcs.plane",
    "history.MetricsHistory.frames_evicted": "gcs.plane",
    "history._Level.frames": "gcs.plane",
    "history._Level.last_ts": "gcs.plane",
    "history._Level.pending_digests": "gcs.plane",
    # --- per-process client (CoreClient)
    "client.CoreClient._futures": "client.req",
    "client.CoreClient._next_req": "client.req",
    "client.CoreClient._ref_counts": "client.ref|static",
    "client.CoreClient._edge_buf": "client.ref|static",
    "client.CoreClient._prov_buf": "client.ref|static",
    "client.CoreClient._sub_buf": "client.sub|static",
    "client.CoreClient._gen_credit": "client.gen_credit",
    "client.CoreClient._pending_decrs":
        "atomic:GC-safe lock-free deque — ObjectRef.__del__ may run "
        "while this thread already holds client.ref",
    "client.CoreClient._registered_fns":
        "atomic:set add/membership are GIL-atomic; a duplicate "
        "registration is an idempotent KV_PUT",
    # --- node service: ONE dispatcher thread owns the scheduling state
    "node.NodeService._pending": "thread:rtpu-dispatch",
    "node._PendingQueue._by_shape": "thread:rtpu-dispatch",
    "node.NodeService._workers": "thread:rtpu-dispatch",
    "node.NodeService._idle": "thread:rtpu-dispatch",
    "node.NodeService._num_starting": "thread:rtpu-dispatch",
    "node.NodeService._env_spawn_failures": "thread:rtpu-dispatch",
    "node.NodeService._env_spawn_error": "thread:rtpu-dispatch",
    "node.NodeService._exec_outbox": "thread:rtpu-dispatch",
    "node.NodeService._reply_outbox": "thread:rtpu-dispatch",
    "node.NodeService._in_batch": "thread:rtpu-dispatch",
    "node.NodeService._route_debits": "thread:rtpu-dispatch",
    "node.NodeService._node_versions": "thread:rtpu-dispatch",
    "node.NodeService._task_origin": "thread:rtpu-dispatch",
    "node.NodeService._waiting_deps": "thread:rtpu-dispatch",
    "node.NodeService._dep_index": "thread:rtpu-dispatch",
    "node.NodeService._running": "thread:rtpu-dispatch",
    "node.NodeService._owned": "thread:rtpu-dispatch",
    "node.NodeService._actors": "thread:rtpu-dispatch",
    "node.NodeService._actor_queues": "thread:rtpu-dispatch",
    "node.NodeService._actor_blocked_owners": "thread:rtpu-dispatch",
    "node.NodeService._get_waiters": "thread:rtpu-dispatch",
    "node.NodeService._wait_waiters": "thread:rtpu-dispatch",
    "node.NodeService._gen_waiters": "thread:rtpu-dispatch",
    "node.NodeService._gen_consumed_cache": "thread:rtpu-dispatch",
    "node.NodeService._gen_local": "thread:rtpu-dispatch",
    "node.NodeService._obj_waiter_index": "thread:rtpu-dispatch",
    "node.NodeService._next_waiter": "thread:rtpu-dispatch",
    "node.NodeService._infeasible": "thread:rtpu-dispatch",
    "node.NodeService._repark_deadline": "thread:rtpu-dispatch",
    "node.NodeService._conn_refs": "thread:rtpu-dispatch",
    "node.NodeService._reconstructing": "thread:rtpu-dispatch",
    "node.NodeService._reroute_parked": "thread:rtpu-dispatch",
    "node.NodeService._conn_kind": "thread:rtpu-dispatch",
    "node.NodeService._conn_worker": "thread:rtpu-dispatch",
    "node.NodeService._conn_coll_wid": "thread:rtpu-dispatch",
    "node.NodeService._coll_conns": "thread:rtpu-dispatch",
    "node.NodeService._driver_conn_keys": "thread:rtpu-dispatch",
    # tick-thread-confined heartbeat state
    "node.NodeService._last_hb_at": "thread:rtpu-tick",
    "node.NodeService._hb_count": "thread:rtpu-tick",
    "node.NodeService._resource_version": "thread:rtpu-tick",
    "node.NodeService._last_hb_snapshot": "thread:rtpu-tick",
    "node.NodeService._last_hb_pending": "thread:rtpu-tick",
    # resource accounting under node.res
    "node.NodeService.resources_available": "node.res",
    "node.NodeService.pg_reservations": "node.res",
    "node.NodeService.pg_bundle_total": "node.res",
    "node.NodeService._tpu_free": "node.res",
    # debug-collection futures under node.debug
    "node.NodeService._debug_futures": "node.debug",
    "node.NodeService._next_debug_token": "node.debug",
    # deliberately lock-free node state
    "node.NodeService._conns":
        "atomic:unique-key inserts from the two accept threads, pops "
        "on the dispatcher; dict ops are GIL-atomic",
    "node.NodeService._coll_peers":
        "atomic:idempotent same-value cache fill from reader threads "
        "(chunk forwarding must not pay a lock per chunk)",
    "node.NodeService._peers":
        "atomic:idempotent cache fill; readers revalidate via each "
        "peer's closed/dead flag",
    "node.NodeService._coll_health_cache":
        "atomic:racy TTL cache — a tuple swap; duplicate diagnosis "
        "fan-outs are the only cost of a lost race",
    # --- worker runtime: exec-thread-confined actor state; the rest is
    # --- deliberately lock-free reader<->exec signalling
    "worker.WorkerRuntime._actor_instance": "thread:task-exec",
    "worker.WorkerRuntime._actor_spec": "thread:task-exec",
    "worker.WorkerRuntime._pool": "thread:task-exec",
    "worker.WorkerRuntime._aio_loop": "thread:task-exec",
    "worker.WorkerRuntime._current_task_thread": "thread:task-exec",
    "worker.WorkerRuntime._functions":
        "atomic:idempotent cache fill; concurrent actor pool threads "
        "may each load the same function blob once",
    "worker.WorkerRuntime._cancelled_queued":
        "atomic:reader thread adds, exec thread discards; set ops are "
        "GIL-atomic and a missed cancel re-runs the cancel path",
    "worker.WorkerRuntime._blocked_in_get":
        "atomic:bool flag written by the exec thread, read by the "
        "reader's bounce check — a stale read only delays one bounce",
    "worker.WorkerRuntime._ckpt_counter":
        "atomic:itertools.count allocation is GIL-atomic; overlapping "
        "re-seeds are benign (documented in checkpoint_now)",
    "worker.WorkerRuntime._ckpt_calls":
        "atomic:periodic-trigger counter; a lost increment delays one "
        "checkpoint by one call",
    "worker.WorkerRuntime._ckpt_last_t":
        "atomic:periodic-trigger stamp, same tolerance as _ckpt_calls",
    "worker.WorkerRuntime._kicker":
        "atomic:benign duplicate kicker if two completions race the "
        "first _ensure_kicker; both just kick the same conn",
    # --- collective chunk mailbox (module-level, under coll.mailbox)
    "coll_transport._slots": "coll.mailbox",
    "coll_transport._born": "coll.mailbox",
    "coll_transport._fenced": "coll.mailbox",
    "coll_transport._next_sweep": "coll.mailbox",
    "coll_transport._stats":
        "atomic:per-field single-writer counters (rank thread / reader "
        "thread); dict slot += is the documented tolerance",
    # --- telemetry shards + runtime registry
    "telemetry._Digest.cents":
        "atomic:a digest instance is owned by its containing table's "
        "lock (telemetry.shard live, gcs.plane on the merge path); "
        "never shared across owners",
    "telemetry._Digest.buf": "atomic:see telemetry._Digest.cents",
    "telemetry._Digest.count": "atomic:see telemetry._Digest.cents",
    "telemetry._Digest.sum": "atomic:see telemetry._Digest.cents",
    "telemetry._Digest.min": "atomic:see telemetry._Digest.cents",
    "telemetry._Digest.max": "atomic:see telemetry._Digest.cents",
    "telemetry._Shard.counters": "telemetry.shard|static",
    "telemetry._Shard.gauges": "telemetry.shard|static",
    "telemetry._Shard.gauges_dirty": "telemetry.shard|static",
    "telemetry._Shard.hists": "telemetry.shard|static",
    "telemetry._Shard.digests": "telemetry.shard|static",
    "telemetry._meta": "telemetry.meta",
    "telemetry._conflict_warned": "telemetry.meta",
    "telemetry._nodes": "telemetry.runtime",
    "telemetry._flusher_started":
        "atomic:double-checked flag — probed lock-free, set under "
        "telemetry.runtime",
    "telemetry._sampler_started":
        "atomic:set-once under telemetry.runtime, probed lock-free",
    "telemetry._last_flush":
        "atomic:rate-limiter stamp; a lost update costs one extra flush",
    "telemetry._last_digest_ship":
        "atomic:rate-limiter stamp for the digest ship cadence",
    "telemetry._digest_gen":
        "atomic:generation bump on reset(); handles re-resolve on "
        "mismatch",
    "telemetry._jax_listener_installed":
        "atomic:set-once latch; a duplicate listener install is "
        "idempotent at the jax API",
    # --- object store
    "object_store.ObjectStore._entries": "store.entries|static",
    "object_store.ObjectStore._used": "store.entries|static",
    "object_store.ObjectStore._quarantine": "store.entries",
    "object_store.ObjectStore.num_spilled": "store.entries",
    "object_store.ObjectStore.num_restored": "store.entries",
    "object_store.ObjectStore.num_lazy_puts": "store.entries",
    "object_store.ObjectStore.num_materialized": "store.entries",
    "object_store.ObjectStore.spilled_bytes_total": "store.entries",
    "object_store.ObjectStore.restored_bytes_total": "store.entries",
    "object_store.ObjectStore._spill_events": "store.entries",
    "object_store.ObjectStore._manifest_f": "store.entries|static",
    "object_store.ObjectReader._segments": "store.reader_segments",
    # --- transport (protocol.Connection)
    "protocol.Connection._outq": "conn.queue|static",
    "protocol.Connection._broken": "conn.queue|static",
    "protocol.Connection._closing": "conn.queue|static",
    "protocol.Connection._recv_buf":
        "atomic:single reader per connection by construction (the "
        "owning process's one recv loop)",
    "protocol.Connection._decoded": 
        "atomic:single reader per connection by construction (decode "
        "buffer of the owning process's one recv loop)",
    "protocol.Connection._oob_scratch":
        "atomic:owned by the active drainer (conn.flush held via the "
        "explicit combining-drainer acquire, invisible to the "
        "with-block pass)",
    "protocol.Connection._stat_flushes":
        "atomic:drainer-owned flush counters, published every 64 "
        "flushes; conn.flush is held via explicit acquire",
    "protocol.Connection._stat_msgs":
        "atomic:drainer-owned, see _stat_flushes",
    "protocol.Connection._stat_bytes":
        "atomic:drainer-owned, see _stat_flushes",
    "protocol.Connection._stat_oob":
        "atomic:drainer-owned, see _stat_flushes",
}

# ------------------------------------------------------------- plumbing

# Fieldsan (RTPU_FIELDSAN) needs the held-lock bookkeeping the _SanLock
# wrappers maintain, so either sanitizer env enables the wrappers; the
# order/hierarchy checks stay coupled (they are accurate and cheap).
_ENABLED = any(
    os.environ.get(var, "").lower() in ("1", "true", "yes", "on")
    for var in ("RTPU_LOCKSAN", "RTPU_FIELDSAN"))
_MODE = os.environ.get("RTPU_LOCKSAN_MODE", "log")

_tls = threading.local()

# Acquisition-order graph over live lock *instances*:
#   id(lock) -> set of id(lock) acquired while it was held.
# _edge_stacks remembers the stack that created each first-seen edge so
# a cycle report can show both sides. _graph_lock is a RAW lock (never
# sanitized — it is the sanitizer). _seen_edges is probed without the
# lock (benign race: a duplicate probe just repeats the locked check).
_graph_lock = threading.Lock()
_edges: Dict[int, set] = {}
_edge_stacks: Dict[tuple, str] = {}
_names: Dict[int, str] = {}
_seen_edges: set = set()

_violations: List[dict] = []
_reported: set = set()


class LockOrderViolation(RuntimeError):
    """Raised at the acquire site in ``raise`` mode."""


def enabled() -> bool:
    return _ENABLED


def set_mode(mode: str) -> str:
    """``log`` (default) or ``raise``; returns the previous mode."""
    global _MODE
    prev, _MODE = _MODE, mode
    return prev


def violations() -> List[dict]:
    return list(_violations)


def clear_violations() -> None:
    _violations.clear()
    _reported.clear()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _fmt_stack(skip: int = 3, limit: int = 12) -> str:
    return "".join(traceback.format_list(
        traceback.extract_stack(limit=limit + skip)[:-skip]))


def _report(kind: str, message: str, extra: Optional[str] = None) -> None:
    rec = {"kind": kind, "message": message,
           "thread": threading.current_thread().name,
           "stack": _fmt_stack(skip=4)}
    _violations.append(rec)
    key = (kind, message)
    if key not in _reported:
        _reported.add(key)
        print(f"[locksan] {kind}: {message} "
              f"(thread {rec['thread']})\n{rec['stack']}"
              + (f"--- other side ---\n{extra}" if extra else ""),
              file=sys.stderr)
    if _MODE == "raise":
        raise LockOrderViolation(f"{kind}: {message}")


from collections import deque as _deque

_dead_ids: "_deque" = _deque()


def _drop_instance(lid: int) -> None:
    """weakref finalizer: record the dead lock for removal from the
    order graph. MUST NOT take _graph_lock — cyclic GC can run this
    finalizer on a thread that is already inside a ``with _graph_lock:``
    block (any allocation there can trigger a collection), and
    _graph_lock is not reentrant. deque.append is atomic and lock-free;
    the next sanitized acquire sweeps the backlog under the lock."""
    _dead_ids.append(lid)


def _sweep_dead_locked() -> None:
    """Drop GC'd locks from the graph; caller holds _graph_lock. (A
    dead id recycled by a new lock before the sweep could briefly
    inherit stale edges — the sweep runs on every first-seen edge, so
    the window is one novel acquisition order.)"""
    while True:
        try:
            lid = _dead_ids.popleft()
        except IndexError:
            return
        _edges.pop(lid, None)
        _names.pop(lid, None)
        for pair in [p for p in _seen_edges if lid in p]:
            _seen_edges.discard(pair)
            _edge_stacks.pop(pair, None)
        for dsts in _edges.values():
            dsts.discard(lid)


def _reachable(src: int, dst: int) -> bool:
    """DFS over the order graph; callers hold _graph_lock."""
    stack, seen = [src], set()
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(_edges.get(cur, ()))
    return False


class _SanLock:
    """Sanitizing wrapper for Lock/RLock; acquire/release mirror the
    stdlib signatures and everything else passes through to the inner
    primitive, so it also serves as a Condition's inner lock
    (``condition()`` below) — Condition's wait/notify then release and
    re-acquire *through* the wrapper, keeping held-state exact across
    waits."""

    __slots__ = ("name", "kind", "level", "_inner", "__weakref__")

    def __init__(self, name: str, kind: str):
        self.name = name
        self.kind = kind
        reg = REGISTRY.get(name)
        self.level = reg[2] if reg is not None else None
        self._inner = (threading.RLock() if kind == "rlock"
                       else threading.Lock())
        import weakref
        weakref.finalize(self, _drop_instance, id(self))

    # ------------------------------------------------------------ checks
    def _check_blocking(self, held: list) -> None:
        if not held:
            return
        if any(h is self for h in held):
            if self.kind != "rlock":
                _report("self-deadlock",
                        f"re-acquiring non-reentrant lock {self.name!r} "
                        "already held by this thread")
            return
        distinct = {id(h): h for h in held}.values()
        for h in distinct:
            if (self.level is not None and h.level is not None
                    and h.level >= self.level):
                _report("hierarchy",
                        f"acquiring {self.name!r} (level {self.level}) "
                        f"while holding {h.name!r} (level {h.level}) — "
                        "declared order is strictly increasing levels")
        me = id(self)
        for h in distinct:
            pair = (id(h), me)
            if pair in _seen_edges:
                continue
            with _graph_lock:
                _sweep_dead_locked()
                if pair in _seen_edges:
                    continue
                _names[id(h)] = h.name
                _names[me] = self.name
                if _reachable(me, id(h)):
                    other = _edge_stacks.get((me, id(h)), "")
                    _report("order-cycle",
                            f"acquiring {self.name!r} while holding "
                            f"{h.name!r}, but the reverse order "
                            f"({self.name!r} before {h.name!r}) was "
                            "already observed — deadlock-capable "
                            "inversion", extra=other)
                _seen_edges.add(pair)
                _edges.setdefault(id(h), set()).add(me)
                _edge_stacks[pair] = _fmt_stack(skip=4)

    # ------------------------------------------------------- lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking and (timeout is None or timeout < 0):
            self._check_blocking(_held())
        if timeout is None:
            timeout = -1
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held().append(self)
            # held-NAME counts beside the instance list: fieldsan's
            # guard check is one dict probe instead of a scan (the
            # probe runs on every declared-field access)
            names = getattr(_tls, "held_names", None)
            if names is None:
                names = _tls.held_names = {}
            names[self.name] = names.get(self.name, 0) + 1
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                names = getattr(_tls, "held_names", None)
                if names is not None:
                    n = names.get(self.name, 1) - 1
                    if n <= 0:
                        names.pop(self.name, None)
                    else:
                        names[self.name] = n
                break
        self._inner.release()

    def __getattr__(self, name):
        # transparent passthrough (``locked`` on plain locks, etc.):
        # the wrapper exposes exactly the inner primitive's surface —
        # on 3.10 RLock has no ``locked``, and neither does its wrapper
        if name == "_inner":        # guard __init__-time recursion
            raise AttributeError(name)
        return getattr(self._inner, name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<SanLock {self.name!r} level={self.level}>"


# ------------------------------------------------------------- factories

def lock(name: str) -> "threading.Lock":
    """A mutex declared under ``name`` (see REGISTRY / DESIGN.md)."""
    if not _ENABLED:
        return threading.Lock()
    return _SanLock(name, "lock")


def rlock(name: str) -> "threading.RLock":
    if not _ENABLED:
        return threading.RLock()
    return _SanLock(name, "rlock")


def condition(name: str, cv_lock=None) -> "threading.Condition":
    """A condition variable declared under ``name``. Pass the lock it
    shares (``cv_lock``) when callers also take that lock directly;
    sanitized conditions must wrap a plain (non-reentrant) lock —
    Condition's default release/re-acquire protocol assumes one."""
    if not _ENABLED:
        return threading.Condition(cv_lock)
    if cv_lock is None:
        cv_lock = _SanLock(name, "lock")
    return threading.Condition(cv_lock)
