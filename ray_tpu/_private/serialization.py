"""Zero-copy serialization for task args, returns and stored objects.

Equivalent role to the reference's serialization layer
(``python/ray/_private/serialization.py`` + cloudpickle + plasma buffer
protocol): we use pickle protocol 5 with out-of-band buffers so that numpy
arrays (and host-side jax.Array data) round-trip without copies when the
destination is a shared-memory segment, and cloudpickle (vendored in
``pickle`` fallback) for closures/lambdas.

Wire format of a serialized object:

    [8B total_len][8B meta_len][meta pickle][buf0][buf1]...

where ``meta`` is ``(payload_pickle_bytes, [buf_len, ...])`` and the
payload pickle references the buffers out-of-band (PickleBuffer).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, List, Tuple

try:
    import cloudpickle as _function_pickler  # provided by the baked-in deps
except Exception:  # pragma: no cover - cloudpickle ships with the image
    import pickle as _function_pickler

_HEADER = struct.Struct("<QQ")


class _MainDetectingPickler(pickle.Pickler):
    """C-speed pickler that flags global references into ``__main__``
    (classes/functions pickled by reference that a worker process could
    never import)."""

    def reducer_override(self, obj):
        if getattr(obj, "__module__", None) == "__main__":
            # abort THIS dump immediately — finishing it just to throw
            # the result away would pay the full pickle twice
            raise pickle.PicklingError("__main__ reference")
        return NotImplemented        # standard reduction continues


def dumps_function(fn) -> bytes:
    """Pickle a function/class including closures (cloudpickle)."""
    return _function_pickler.dumps(fn)


def loads_function(data: bytes):
    return pickle.loads(data)


def serialize(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """Serialize to (meta_bytes, out_of_band_buffers).

    Buffers are memoryviews into the original object's storage — the caller
    writes them into shm (or the socket) without an intermediate copy.

    Plain pickle is the fast path, but it "succeeds" on
    ``__main__``-defined classes/functions by pickling them BY REFERENCE,
    which then fails to resolve in a worker whose ``__main__`` is the
    worker module. A reducer_override hook detects actual global
    references into ``__main__`` (no false positives on data that merely
    CONTAINS the string) and redoes those — and anything plain pickle
    rejects outright — with cloudpickle, which pickles by value.
    """
    buffers: List[pickle.PickleBuffer] = []
    try:
        f = io.BytesIO()
        pickler = _MainDetectingPickler(f, protocol=5,
                                        buffer_callback=buffers.append)
        pickler.dump(obj)
        payload = f.getvalue()
    except (pickle.PicklingError, AttributeError, TypeError):
        buffers = []
        payload = _function_pickler.dumps(obj, protocol=5,
                                          buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    meta = pickle.dumps((payload, [len(v) for v in views]), protocol=5)
    return meta, views


def serialized_size(meta: bytes, views: List[memoryview]) -> int:
    return _HEADER.size + len(meta) + sum(len(v) for v in views)


def write_to(buf: memoryview, meta: bytes, views: List[memoryview]) -> int:
    """Write the full wire format into ``buf``; returns bytes written."""
    total = serialized_size(meta, views)
    _HEADER.pack_into(buf, 0, total, len(meta))
    off = _HEADER.size
    buf[off:off + len(meta)] = meta
    off += len(meta)
    for v in views:
        n = len(v)
        buf[off:off + n] = v.cast("B") if v.format != "B" or v.ndim != 1 else v
        off += n
    return total


def write_file(f, meta: bytes, views: List[memoryview]) -> int:
    """Stream the wire format to a file object without assembling a
    contiguous buffer first (used to spill a lazy object straight from
    the owner's heap to disk); returns bytes written."""
    total = serialized_size(meta, views)
    f.write(_HEADER.pack(total, len(meta)))
    f.write(meta)
    for v in views:
        f.write(v.cast("B") if v.format != "B" or v.ndim != 1 else v)
    return total


def to_bytes(obj: Any) -> bytes:
    """One-shot serialize into a contiguous bytes object."""
    meta, views = serialize(obj)
    out = bytearray(serialized_size(meta, views))
    write_to(memoryview(out), meta, views)
    return bytes(out)


def read_from(buf: memoryview) -> Any:
    """Deserialize from the wire format. Buffers are zero-copy views into
    ``buf`` — keep the backing storage alive while the object is in use
    (numpy arrays returned from shm keep a reference via the memoryview)."""
    total, meta_len = _HEADER.unpack_from(buf, 0)
    off = _HEADER.size
    meta = bytes(buf[off:off + meta_len])
    off += meta_len
    payload, buf_lens = pickle.loads(meta)
    oob = []
    for n in buf_lens:
        oob.append(buf[off:off + n])
        off += n
    return pickle.loads(payload, buffers=oob)


def from_bytes(data: bytes) -> Any:
    return read_from(memoryview(data))
